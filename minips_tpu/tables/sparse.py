"""SparseTable — fixed-capacity hashed embedding replacing MapStorage.

The reference's sparse path is ``MapStorage<Val>`` — a per-server
``std::map<key, val>`` grown on demand (SURVEY.md §2 "KVTable storage").
TPUs have no dynamic dictionaries: XLA needs static shapes. The TPU-native
equivalent (SURVEY.md §7.1) is a fixed-slot embedding matrix
``[num_slots, dim]`` with multiplicative hashing of the (unbounded) feature
id space onto slots — the standard "hashing trick" used by production CTR
systems for exactly this workload family (Criteo W&D/DeepFM,
BASELINE.json:10).

Sharding: rows are range-partitioned across the mesh ``data`` axis
(``PartitionSpec('data', None)``) — the same contiguous-range server
partition as the reference's RangeManager, but expressed as a sharding so
XLA GSPMD inserts the gather/scatter collectives (SURVEY.md §2.3; PAPERS.md
SparCML is the sparse-collective analog).

``pull(keys)`` is a row gather; ``push(keys, grads)`` scatter-adds duplicate
keys (reference ``Add`` semantics) and applies the server-side updater.
Per-row lazy updates for Adagrad keep push cost O(batch · dim) instead of
O(num_slots · dim) — the reference's per-key server update has the same
sparsity property.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from minips_tpu.parallel.mesh import DATA_AXIS

_HASH_MULT = np.uint32(2654435761)  # Knuth multiplicative hash


def hash_to_slots(keys: jnp.ndarray, num_slots: int, salt: int = 0,
                  identity: bool = False) -> jnp.ndarray:
    """Hash arbitrary int feature ids onto [0, num_slots). num_slots must be
    a power of two (masked multiply-shift hash, cheap on VPU).

    ``identity=True`` skips the hash and maps key → key & (num_slots-1):
    exact per-key rows (the reference's MapStorage gives every key its own
    entry) for already-dense 0-based id spaces that fit the table, while the
    mask keeps any stray key in range."""
    assert num_slots & (num_slots - 1) == 0, "num_slots must be a power of 2"
    k = keys.astype(jnp.uint32)
    if identity:
        return (k & jnp.uint32(num_slots - 1)).astype(jnp.int32)
    h = (k * _HASH_MULT) ^ (k >> 16) ^ jnp.uint32(salt)
    return (h & jnp.uint32(num_slots - 1)).astype(jnp.int32)


def hash_to_slots_np(keys: np.ndarray, num_slots: int, salt: int = 0,
                     identity: bool = False) -> np.ndarray:
    """NumPy twin of :func:`hash_to_slots` for host-side key routing (the
    sharded multi-process PS hashes before splitting by owner — no device
    round-trip). Bit-identical to the jax version by test."""
    assert num_slots & (num_slots - 1) == 0, "num_slots must be a power of 2"
    k = np.asarray(keys).astype(np.uint32)
    if identity:
        return (k & np.uint32(num_slots - 1)).astype(np.int64)
    h = (k * _HASH_MULT) ^ (k >> np.uint32(16)) ^ np.uint32(salt)
    return (h & np.uint32(num_slots - 1)).astype(np.int64)


def collision_stats(keys: np.ndarray, num_slots: int, salt: int = 0,
                    identity: bool = False,
                    max_sample: int = 1 << 20) -> dict:
    """Measured key→slot collision accounting for a hashed table
    (VERDICT r2 Missing #3): the reference's MapStorage gives every key
    its own row, while the fixed-slot hash (SURVEY.md §7.1) silently
    merges colliding keys' parameters — invisible quality degradation
    unless it is *measured*. Apps log this once per run over (a sample
    of) their key stream.

    Returns ``unique_keys`` U, ``unique_slots`` (slots those keys occupy),
    ``collision_rate`` = 1 − occupied/U — the fraction of unique keys
    FOLDED into an already-occupied slot (an m-key slot contributes m−1;
    0 means every key owns its row; identity mode on a dense id space is
    exactly 0 by construction), and ``expected_rate`` for a uniform
    random hash (1 − S(1−(1−1/S)^U)/U) so an anomalously clumpy hash is
    visible against its own baseline. Sizing guidance (docs/api.md):
    keep slots ≥ 4× expected unique keys for a ~12% worst-case rate,
    ≥ 16× for ~3%.
    """
    k = np.asarray(keys).reshape(-1)
    sampled = k.size > max_sample
    if sampled:
        # deterministic WITH-replacement sample: O(max_sample), not a
        # full-stream permutation (a 100M-key run must not pay O(N)
        # memory at startup); statistically equivalent for this estimate
        k = k[np.random.default_rng(0).integers(0, k.size,
                                                size=max_sample)]
    uniq = np.unique(k)
    u = int(uniq.size)
    occupied = int(np.unique(
        hash_to_slots_np(uniq, num_slots, salt, identity)).size)
    s = float(num_slots)
    expected = 0.0 if identity or u == 0 else \
        1.0 - s * (1.0 - (1.0 - 1.0 / s) ** u) / u
    return {
        "unique_keys": u,
        "unique_slots": occupied,
        "num_slots": int(num_slots),
        "collision_rate": round(1.0 - occupied / max(u, 1), 6),
        "expected_rate": round(expected, 6),
        "sampled": sampled,
    }


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two ≥ max(n, floor) — SparseTable capacities must
    be powers of two (masked hash above)."""
    n = max(int(n), int(floor), 1)
    return 1 << (n - 1).bit_length()


class SparseTable:
    """Hashed, sharded embedding table with server-side SGD/Adagrad on push."""

    def __init__(
        self,
        num_slots: int,
        dim: int,
        mesh: Mesh,
        *,
        name: str = "sparse0",
        updater: str = "sgd",
        lr: float = 0.05,
        init_scale: float = 0.01,
        adagrad_init: float = 0.1,
        salt: int = 0,
        identity: bool = False,
        seed: int = 0,
        dtype=jnp.float32,
        use_pallas: Optional[bool] = None,
    ):
        if updater not in ("sgd", "adagrad", "adam"):
            raise ValueError(
                "sparse updater must be 'sgd', 'adagrad', or 'adam'")
        self.name = name
        self.mesh = mesh
        self.num_slots = int(num_slots)
        self.dim = int(dim)
        self.updater = updater
        self.lr = lr
        self.adagrad_init = adagrad_init
        self.salt = salt
        # exact per-key rows for dense 0-based id spaces (reference
        # MapStorage semantics — no hash collisions); see hash_to_slots
        self.identity = identity

        # Pallas gather opt-in, resolved ONCE here (the jitted pull is
        # trace-cached, so a late env toggle would be silently ignored).
        # Single-device meshes only: pallas_call has no GSPMD partitioning
        # rule, so on a sharded table it would force a full replication
        # all-gather of emb instead of the sharded XLA gather. The backend
        # check applies even to an explicit use_pallas=True — the kernel
        # uses pltpu primitives, which fail Mosaic lowering off-TPU.
        from minips_tpu.ops import pallas_kernels as _pk

        n_dev = len(np.asarray(mesh.devices).reshape(-1))
        self.use_pallas = bool(
            (use_pallas if use_pallas is not None else _pk.pallas_enabled())
            and n_dev == 1 and _pk.backend_supported())

        self._sharding = NamedSharding(mesh, P(DATA_AXIS, None))
        key = jax.random.PRNGKey(seed)
        emb = jax.random.normal(key, (self.num_slots, self.dim), dtype) * init_scale
        self.emb = jax.device_put(emb, self._sharding)
        self.accum = None
        self.m = self.v = self.steps = None
        if updater == "adagrad":
            self.accum = jax.device_put(
                jnp.full((self.num_slots, self.dim), adagrad_init, dtype),
                self._sharding,
            )
        elif updater == "adam":  # row-wise LAZY adam: moments + per-row t
            zeros = jnp.zeros((self.num_slots, self.dim), dtype)
            self.m = jax.device_put(zeros, self._sharding)
            self.v = jax.device_put(zeros, self._sharding)
            self.steps = jax.device_put(
                jnp.zeros((self.num_slots,), jnp.int32),
                NamedSharding(mesh, P(DATA_AXIS)))

    # --------------------------------------------------- unified opt state
    # (emb,) + opt_state() is the table's full tuple; row_update is the
    # pure per-push transition both SparseTable.push and the fused
    # PSTrainStep use, so the two paths cannot drift numerically.
    def opt_state(self) -> tuple:
        if self.updater == "adagrad":
            return (self.accum,)
        if self.updater == "adam":
            return (self.m, self.v, self.steps)
        return ()

    def set_opt_state(self, opt: tuple) -> None:
        if self.updater == "adagrad":
            (self.accum,) = opt
        elif self.updater == "adam":
            self.m, self.v, self.steps = opt

    def row_update(self, emb, opt: tuple, slots, grads):
        """Pure updater: (emb', opt') for one push of already-hashed slots.
        Traceable under jit; duplicates follow the reference's
        sum-then-update server semantics."""
        from minips_tpu.ops.sparse_update import (row_adagrad, row_adam,
                                                  row_sgd)

        if self.updater == "sgd":
            return row_sgd(emb, slots, grads, self.lr), ()
        if self.updater == "adagrad":
            (accum,) = opt
            emb, accum = row_adagrad(emb, accum, slots, grads, self.lr)
            return emb, (accum,)
        m, v, steps = opt
        emb, m, v, steps = row_adam(emb, m, v, steps, slots, grads, self.lr)
        return emb, (m, v, steps)

    # ------------------------------------------------------------------ hash
    def slots_of(self, keys: jnp.ndarray) -> jnp.ndarray:
        return hash_to_slots(jnp.asarray(keys), self.num_slots, self.salt,
                             self.identity)

    # ------------------------------------------------------------------ pull
    def pull(self, keys: jnp.ndarray) -> jnp.ndarray:
        """Gather embedding rows for (hashed) keys — KVClientTable::Pull for
        sparse tables (SURVEY.md §2 "KVClientTable"). [B] or [B, F] keys →
        [..., dim] rows."""
        return self._jit_pull(self.emb, jnp.asarray(keys))

    @functools.cached_property
    def _jit_pull(self):
        from minips_tpu.ops import pallas_kernels

        @jax.jit
        def pull(emb, keys):
            slots = self.slots_of(keys)
            if (self.use_pallas
                    and pallas_kernels.gather_supported(self.dim, slots.size)):
                # opt-in hand-scheduled DMA gather; XLA native is the
                # measured default (ops/pallas_kernels.py docstring)
                rows = pallas_kernels.gather_rows(emb, slots.reshape(-1))
                return rows.reshape(*slots.shape, self.dim)
            return emb[slots]
        return pull

    # ------------------------------------------------------------------ push
    def push(self, keys: jnp.ndarray, grads: jnp.ndarray) -> None:
        """Scatter-add grads for (hashed) keys and apply the updater to the
        touched rows only — the reference's per-key server update
        (SURVEY.md §3.3 ``updater->Update(keys, grads)``)."""
        self.emb, new_opt = self._jit_push(
            self.emb, self.opt_state(), jnp.asarray(keys),
            jnp.asarray(grads))
        self.set_opt_state(new_opt)

    @functools.cached_property
    def _jit_push(self):
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def push(emb, opt, keys, grads):
            slots = self.slots_of(keys)
            return self.row_update(emb, opt, slots, grads)
        return push

    # ------------------------------------------------------------- state I/O
    _OPT_KEYS = {"adagrad": ("accum",), "adam": ("m", "v", "steps"),
                 "sgd": ()}

    def _layout(self) -> list:
        """[salt, identity] — salt normalized to 0 on the identity path,
        where hash_to_slots never reads it."""
        return [0 if self.identity else self.salt, int(self.identity)]

    def state_dict(self) -> dict:
        out = {"emb": np.asarray(self.emb),
               # key→slot layout: a checkpoint written under one layout is
               # garbage under another (every row lands at a different slot)
               "layout": np.asarray(self._layout(), np.int64)}
        for k in self._OPT_KEYS[self.updater]:
            out[k] = np.asarray(getattr(self, k))
        return out

    def load_state_dict(self, state: dict) -> None:
        missing = [k for k in self._OPT_KEYS[self.updater]
                   if k not in state]
        if missing:
            raise ValueError(
                f"checkpoint lacks sparse optimizer state {missing} for "
                f"updater {self.updater!r} (written by a different "
                "updater?)")
        want = self._layout()
        if "layout" in state:
            got = np.asarray(state["layout"]).tolist()
            if got != want:
                raise ValueError(
                    f"checkpoint key→slot layout [salt, identity]={got} "
                    f"does not match this table's {want} — rows would "
                    "restore to different slots")
        elif self.identity or self.salt != 0:
            # legacy checkpoints carry no layout record; only the default
            # hashed layout (salt=0) can be assumed — anything else risks
            # silently loading rows under a different key→slot mapping
            raise ValueError(
                "checkpoint predates layout metadata (default hashed "
                f"layout) but this table uses {want} — cannot verify the "
                "key→slot mapping matches")
        self.emb = jax.device_put(jnp.asarray(state["emb"]), self._sharding)
        for k in self._OPT_KEYS[self.updater]:
            cur = getattr(self, k)
            setattr(self, k, jax.device_put(
                jnp.asarray(state[k]), cur.sharding))
