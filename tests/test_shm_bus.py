"""Shared-memory ring transport (comm/shm_bus.py) — this PR's tentpole.

Three tiers, all in-proc (threads as nodes — the reference's own test
idiom, and what keeps these in tier-1):

- ring mechanics: directed/broadcast delivery with blobs across wrap
  boundaries, per-link FIFO order, backpressure-when-full (bounded,
  counted — never silent), oversize rejection at the source, segment
  creation/attach/unlink lifecycle, the stale-run sweeper;
- layer composition: seeded chaos(drop>=1%)+reliable on the shm backend
  completes with zero unrecovered frames (TRANSPORT-COMPOSE's claim,
  proven at bus level), and the layers are the SAME objects make_bus
  stacks on zmq;
- the acceptance drill: a BSP lockstep sharded-PS run over shm is
  BITWISE equal to the same run over zmq (the chaos drill harness,
  reused) — the transport may change how bytes move, never what they
  say.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from tests.conftest import mk_loopback_buses


def _seg_dir():
    # the bus's own directory resolution (/dev/shm when present, else
    # tempdir — the macOS-on-x86 fallback the TSO check permits)
    from minips_tpu.comm import shm_bus
    return shm_bus._shm_dir()


def _seg_files():
    return {f for f in os.listdir(_seg_dir())
            if f.startswith("minips_bus_")}


def _mk(n, **kw):
    buses = mk_loopback_buses(n, backend="shm", settle=0.05, **kw)
    ts = [threading.Thread(target=b.handshake, args=(n,)) for b in buses]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=15.0)
    assert not any(t.is_alive() for t in ts), "shm handshake wedged"
    return buses


def _close(buses):
    for b in buses:
        b.close()


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert pred(), "timed out waiting for frames"


# ------------------------------------------------------------ ring basics
def test_directed_broadcast_and_blobs_deliver_in_order():
    buses = _mk(3)
    got: list = []
    gotb = {0: [], 2: []}
    buses[1].on("x", lambda s, p: got.append((s, p["i"],
                                              p.get("__blob__"))))
    for r in (0, 2):
        buses[r].on("bc", (lambda r: lambda s, p:
                           gotb[r].append(p["i"]))(r))
    arr = np.arange(4096, dtype=np.float32)
    try:
        for i in range(200):
            buses[0].send(1, "x", {"i": i, "acks": [i, i + 1]},
                          blob=arr.tobytes() if i % 3 == 0 else None)
            buses[1].publish("bc", {"i": i})
        _wait(lambda: len(got) >= 200 and all(len(v) >= 200
                                              for v in gotb.values()))
        assert [g[1] for g in got] == list(range(200))  # per-link FIFO
        assert gotb[0] == list(range(200)) == gotb[2]
        blobs = [g[2] for g in got if g[2] is not None]
        assert len(blobs) == 67
        for b in blobs:  # bitwise through the ring, every wrap included
            assert np.array_equal(np.frombuffer(b, np.float32), arr)
        assert all(b.frames_lost == 0 for b in buses)
        assert all(b.frames_malformed == 0 for b in buses)
    finally:
        _close(buses)


def test_wrap_boundary_survives_large_blob_stream(monkeypatch):
    """Frames sized to force many wrap-marker paths through the ring:
    every byte must land bitwise intact, in order."""
    monkeypatch.setenv("MINIPS_SHM_RING", str(1 << 18))
    buses = _mk(2)  # 256KiB ring, ~90KiB frames
    got: list = []
    buses[1].on("big", lambda s, p: got.append((p["i"],
                                                p["__blob__"])))
    rng = np.random.default_rng(7)
    payloads = [rng.integers(0, 255, size=90_000).astype(np.uint8)
                for _ in range(24)]
    try:
        for i, arr in enumerate(payloads):
            buses[0].send(1, "big", {"i": i}, blob=arr.tobytes())
        _wait(lambda: len(got) >= 24)
        assert [g[0] for g in got] == list(range(24))
        for (_, blob), arr in zip(got, payloads):
            assert np.array_equal(np.frombuffer(blob, np.uint8), arr)
        assert buses[0].send_drops == 0  # backpressure blocked, not lost
    finally:
        _close(buses)


def test_oversize_frame_rejected_at_source(monkeypatch):
    monkeypatch.setenv("MINIPS_SHM_RING", str(1 << 16))
    buses = _mk(2)
    try:
        with pytest.raises(ValueError, match="MINIPS_SHM_RING"):
            buses[0].send(1, "x", {}, blob=b"z" * (1 << 16))
        # the stream stays live and gap-free after the raise: the
        # rejected frame's seq stamp is ROLLED BACK (native ordering —
        # a consumed-but-never-sent seq would read as a permanent wire
        # drop under the reliable layer's NACK/GONE machinery)
        got: list = []
        buses[1].on("x", lambda s, p: got.append(p["i"]))
        buses[0].send(1, "x", {"i": 1})
        _wait(lambda: got == [1])
        assert buses[1].frames_lost == 0
        assert buses[0]._dseq[1] == 1  # oversize send consumed no seq
    finally:
        _close(buses)


def test_near_cap_frame_reserves_retransmit_wrapper(monkeypatch):
    """A journaled frame sized within the record cap but whose __rt
    retransmit wrapper would NOT fit must be rejected at first send:
    otherwise the NACK-path re-send raises on the recv thread (where
    dispatch swallows it), the retransmit never goes out, and the
    stream stalls to give-up — unrecovered loss on a reliable run.
    Without the reliable layer no retransmit can exist, so the same
    frame must still be accepted."""
    from minips_tpu.comm import framing

    monkeypatch.setenv("MINIPS_SHM_RING", str(1 << 16))
    buses = _mk(2, reliable="1")
    try:
        cap = buses[0]._max_rec
        head = {"kind": "x", "sender": 0, "payload": {}, "ds": 0}
        msg = framing.encode_head(head, buses[0].wire_fmt)
        wmsg = framing.encode_head(
            {"kind": "__rt", "sender": 0, "payload": framing.rt_wrap(msg)},
            buses[0].wire_fmt)
        ov = len(wmsg) - len(msg)  # the wrapper's head-byte overhead
        assert ov > 0
        # raw record = 4 + 4 + len(msg) + 8 + blen: land it cap - ov//2
        # under the cap — fits bare, cannot fit re-wrapped
        blen = cap - 16 - len(msg) - ov // 2
        with pytest.raises(ValueError, match="MINIPS_SHM_RING"):
            buses[0].send(1, "x", {}, blob=b"z" * blen)
        # stream stays live and gap-free: the seq stamp rolled back
        got: list = []
        buses[1].on("x", lambda s, p: got.append(p["i"]))
        buses[0].send(1, "x", {"i": 1})
        _wait(lambda: got == [1])
        assert buses[1].frames_lost == 0
        assert buses[0]._dseq[1] == 1
    finally:
        _close(buses)
    # no reliable layer ⇒ no journal, no retransmit: same frame sends
    buses = _mk(2)
    got2: list = []
    buses[1].on("x", lambda s, p: got2.append(len(p["__blob__"])))
    try:
        buses[0].send(1, "x", {}, blob=b"z" * blen)
        _wait(lambda: got2 == [blen])
    finally:
        _close(buses)


def test_segment_lifecycle_create_unlink_and_sweep():
    from minips_tpu.comm import shm_bus

    before = _seg_files()
    buses = _mk(2)
    ns_files = _seg_files() - before
    assert len(ns_files) == 4  # 2 rings + 2 doorbells
    _close(buses)
    after = _seg_files()
    assert not (after - before), "close() leaked segments"
    # the sweeper reclaims a dead run's leftovers but spares live ones
    dead = os.path.join(_seg_dir(),
                        "minips_bus_999999999_feed_0to1.ring")
    live = os.path.join(_seg_dir(),
                        f"minips_bus_{os.getpid()}_feed_0to1.ring")
    for p in (dead, live):
        with open(p, "wb") as f:
            f.write(b"\0" * 128)
    try:
        shm_bus.sweep_stale_segments()
        assert not os.path.exists(dead)
        assert os.path.exists(live)
    finally:
        for p in (dead, live):
            try:
                os.unlink(p)
            except OSError:
                pass


def test_post_close_publish_is_silent_noop():
    buses = _mk(2)
    _close(buses)
    buses[0].publish("x", {"i": 1})  # zmq-parity: no use-after-close


def test_empty_ring_env_knob_means_default(monkeypatch):
    """MINIPS_SHM_RING="" is DEFAULT, like every other MINIPS_* knob
    (the bench arms pin empty strings to keep an armed environment
    from leaking) — int('') must not blow up construction."""
    from minips_tpu.comm import shm_bus

    monkeypatch.setenv("MINIPS_SHM_RING", "")
    buses = _mk(2)
    try:
        assert all(b._cap == shm_bus.DEFAULT_RING for b in buses)
    finally:
        _close(buses)


def test_recv_thread_send_budget_is_bounded(monkeypatch):
    """A send issued from the recv thread (handler replies, reliable
    NACK/retransmit) must not sit the full 30s backpressure budget: it
    stops draining inbound rings while it waits (for ring space or its
    write turn — the seq lock itself is never held across the wait),
    so two symmetric recv threads would stall each other for
    the whole budget. The recv-thread budget is recv_send_timeout
    (250ms) and the overflow drops COUNTED — journal+NACK (or the
    pull-deadline poison) owns recovery."""
    monkeypatch.setenv("MINIPS_SHM_RING", str(1 << 16))
    buses = _mk(2)
    real_thread = buses[0]._thread
    try:
        # park the consumer so the 0->1 ring genuinely fills
        buses[1]._stop.set()
        buses[1]._thread.join(timeout=5.0)
        # impersonate the recv thread: _write keys the budget off it
        buses[0]._thread = threading.current_thread()
        blob = b"z" * 8000
        t0 = time.monotonic()
        for i in range(20):  # ~160KB into a 64KiB ring: must overflow
            buses[0].send(1, "x", {"i": i}, blob=blob)
        dt = time.monotonic() - t0
        assert buses[0].send_drops > 0  # counted, never silent
        # full-budget behavior would be 30s PER overflowing frame
        assert dt < 15.0, f"recv-thread sends blocked {dt:.1f}s"
    finally:
        buses[0]._thread = real_thread
        _close(buses)


def test_repair_thread_sends_get_short_budget(monkeypatch):
    """The reliable repair thread dispatches recovered frames' handlers
    while holding the channel lock the recv thread's on_stamped needs
    (reliable.py pump -> _drain): its sends must ride the recv thread's
    short budget, or two ranks' repair threads stuck writing into each
    other's full ring would hold both locks for the full 30s budget and
    neither recv thread could drain — the symmetric stall the
    recv_send_timeout exists to break, re-formed one lock up."""
    monkeypatch.setenv("MINIPS_SHM_RING", str(1 << 16))
    buses = _mk(2, reliable="1")
    try:
        # install() registered the repair thread at construction
        assert buses[0].reliable._thread in buses[0]._drain_critical
        # park the consumer so the 0->1 ring genuinely fills, then send
        # from a registered drain-critical thread: the budget must be
        # recv_send_timeout, not the 30s default
        buses[1]._stop.set()
        buses[1]._thread.join(timeout=5.0)
        buses[0].note_drain_critical(threading.current_thread())
        blob = b"z" * 8000
        t0 = time.monotonic()
        for i in range(20):  # ~160KB into a 64KiB ring: must overflow
            buses[0].send(1, "x", {"i": i}, blob=blob)
        dt = time.monotonic() - t0
        assert buses[0].send_drops > 0  # counted, never silent
        assert dt < 15.0, f"drain-critical sends blocked {dt:.1f}s"
    finally:
        _close(buses)


def test_shm_refuses_weakly_ordered_hosts(monkeypatch):
    """The lock-free cursor protocol's data-then-head visibility order
    is an x86-TSO property; pure Python can emit no release fence, so
    a weakly-ordered host (aarch64) could dispatch torn frames.
    Construction must refuse LOUDLY there, not deliver garbage."""
    from minips_tpu.comm import shm_bus

    monkeypatch.setattr(shm_bus.platform, "machine", lambda: "aarch64")
    with pytest.raises(RuntimeError, match="TSO"):
        shm_bus.ShmControlBus("tcp://127.0.0.1:19001",
                              ["tcp://127.0.0.1:19002"], my_id=0)
    # 32-bit x86 is TSO but splits the 8-byte cursor store into two
    # 4-byte moves — a peer can read a torn cursor, so refuse there too
    monkeypatch.setattr(shm_bus.platform, "machine", lambda: "i686")
    with pytest.raises(RuntimeError, match="TSO"):
        shm_bus.ShmControlBus("tcp://127.0.0.1:19001",
                              ["tcp://127.0.0.1:19002"], my_id=0)


def test_backpressured_send_does_not_hold_seq_lock(monkeypatch):
    """The seq lock is never held across a full ring's backpressure
    wait: a blocked producer holding it would stall every other sender
    on the lock itself — where no per-thread budget can apply — so the
    recv thread would stop draining and the symmetric stall would
    re-form one level up from the ring wait."""
    monkeypatch.setenv("MINIPS_SHM_RING", str(1 << 16))
    buses = _mk(2)
    try:
        buses[1]._stop.set()  # park the consumer: the ring will fill
        buses[1]._thread.join(timeout=5.0)
        buses[0].send_timeout = 0.5
        blob = b"z" * 8000
        done = threading.Event()

        def flood():
            for i in range(20):  # ~160KB into 64KiB: overflows mid-way
                buses[0].send(1, "x", {"i": i}, blob=blob)
            done.set()

        t = threading.Thread(target=flood, daemon=True)
        t.start()
        time.sleep(0.25)  # flood is now inside a backpressure wait
        assert not done.is_set(), "ring never filled — test is vacuous"
        assert buses[0]._seq_lock.acquire(timeout=1.0), \
            "seq lock held across ring backpressure"
        buses[0]._seq_lock.release()
        t.join(timeout=30.0)
        assert done.is_set()
        assert buses[0].send_drops > 0
    finally:
        _close(buses)


def test_concurrent_senders_preserve_per_link_stream_integrity():
    """Multiple sender threads share the tx rings in real runs (train
    thread, recv-thread replies, the reliable repair thread): the
    write tickets must keep delivery exactly-once with zero gaps/dups
    and per-thread FIFO intact, whatever the interleaving."""
    buses = _mk(2)
    got: list = []
    buses[1].on("x", lambda s, p: got.append(p["i"]))
    try:
        def flood(base):
            for i in range(150):
                buses[0].send(1, "x", {"i": base + i})

        ts = [threading.Thread(target=flood, args=(k * 1000,))
              for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30.0)
        _wait(lambda: len(got) >= 600)
        assert sorted(got) == sorted(k * 1000 + i for k in range(4)
                                     for i in range(150))
        assert buses[1].frames_lost == 0
        assert buses[1].loss.dups == 0
        assert buses[0].send_drops == 0
        for k in range(4):  # ring order == stamp order per sender
            mine = [i for i in got if i // 1000 == k]
            assert mine == sorted(mine)
    finally:
        _close(buses)


def test_close_with_held_view_still_unlinks_segments():
    """A recv thread that outlives close()'s bounded join still holds
    views into the maps — mm.close() raises BufferError. The segment
    FILES must unlink regardless: a live-pid leak in /dev/shm is
    invisible to the stale-run sweeper."""
    before = _seg_files()
    buses = _mk(2)
    held = buses[0]._rx[1].buf[0:8]  # simulates an in-flight record view
    _close(buses)
    after = _seg_files()
    assert not (after - before), "close() leaked segments under a view"
    held.release()


# ------------------------------------------------------- layer composition
def test_chaos_reliable_compose_on_shm_exactly_once_in_order():
    """TRANSPORT-COMPOSE at bus level: the seeded injector drops/dups/
    reorders on the shm receive path, the reliable channel repairs —
    every frame exactly once, in per-link order, zero unrecovered loss,
    with the counters proving the layer (not luck) carried it."""
    spec = "424242:drop=0.05,dup=0.02,reorder=0.03,delay=0.02," \
           "delay_ms=10"
    buses = _mk(2, chaos=spec, reliable="1")
    got: list = []
    buses[1].on("x", lambda s, p: got.append(p["i"]))
    try:
        n = 300
        for i in range(n):
            buses[0].send(1, "x", {"i": i})
        _wait(lambda: len(got) >= n, timeout=30.0)
        assert got == list(range(n))
        assert buses[1].frames_lost == 0
        ch = buses[1].chaos.snapshot()
        rl = buses[1].reliable.snapshot()
        assert ch["dropped"] > 0, ch
        assert rl["retransmits_got"] > 0, rl
    finally:
        _close(buses)


def test_chaos_without_retransmit_loses_frames_loudly_on_shm():
    """The honest before/after on the new transport too: same chaos
    schedule, reliable off — frames are lost AND counted."""
    buses = _mk(2, chaos="77:drop=0.1")
    got: list = []
    buses[1].on("x", lambda s, p: got.append(p["i"]))
    try:
        for i in range(200):
            buses[0].send(1, "x", {"i": i})
        deadline = time.monotonic() + 10
        last = -1
        while time.monotonic() < deadline:
            time.sleep(0.25)
            if len(got) == last:
                break
            last = len(got)
        assert len(got) < 200
        assert buses[1].frames_lost > 0
        assert buses[1].chaos.snapshot()["dropped"] > 0
    finally:
        _close(buses)


# --------------------------------------------------- the acceptance drill
def test_bsp_lockstep_zmq_vs_shm_is_bitwise_equal():
    """ACCEPTANCE: the same BSP lockstep sharded-PS run (the chaos
    drill's harness, tests/test_chaos_reliable.py) over zmq and over
    shm ends in BITWISE-identical replicas on both ranks — the
    transport moves bytes differently, it may not change one bit of
    training state."""
    from tests.test_chaos_reliable import run_bsp_lockstep

    w_zmq, lost_zmq = run_bsp_lockstep(backend="zmq")
    w_shm, lost_shm = run_bsp_lockstep(backend="shm")
    assert lost_zmq == [0, 0] and lost_shm == [0, 0]
    for a, b in zip(w_zmq, w_shm):
        np.testing.assert_array_equal(a, b)  # bitwise, not allclose


def test_bsp_lockstep_shm_survives_seeded_chaos_bitwise():
    """Chaos(drop>=1%)+reliable ON THE SHM BACKEND reconstructs the
    exact frame stream: bitwise equality against the clean zmq run —
    the full layer-composition claim (transport x chaos x reliable),
    proven, not assumed."""
    from tests.test_chaos_reliable import run_bsp_lockstep

    w_clean, _ = run_bsp_lockstep(backend="zmq")
    w_chaos, lost = run_bsp_lockstep(
        backend="shm", chaos="31337:drop=0.04,dup=0.02,reorder=0.03",
        reliable="1")
    assert lost == [0, 0]
    for a, b in zip(w_clean, w_chaos):
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------------- loopback lane
def test_loopback_send_to_self_delivers_without_ring():
    """rank→self rides the in-process loopback lane: delivered on the
    recv thread in FIFO order, blob materialized, zero ring/wire bytes
    (the serving plane's local-replica transport win)."""
    buses = _mk(2)
    got: list = []
    threads: set = set()
    buses[0].on("self", lambda s, p: (got.append((s, p["i"],
                                                  p.get("__blob__"))),
                                      threads.add(
                                          threading.current_thread())))
    sent0 = buses[0].bytes_sent
    try:
        arr = np.arange(64, dtype=np.int64)
        for i in range(50):
            buses[0].send(0, "self", {"i": i},
                          blob=arr.tobytes() if i % 2 else None)
        _wait(lambda: len(got) >= 50)
        assert [g[1] for g in got] == list(range(50))  # FIFO
        assert all(g[0] == 0 for g in got)  # sender is myself
        for g in got:
            if g[2] is not None:
                assert np.array_equal(np.frombuffer(g[2], np.int64),
                                      arr)
        assert buses[0].bytes_sent == sent0  # nothing crossed a wire
        assert buses[0].loopback_frames == 50
        assert threads == {buses[0]._thread}  # recv-thread dispatch
        assert buses[0].frames_lost == 0
    finally:
        _close(buses)


def test_loopback_payload_is_deep_copied():
    """The handler's payload must not alias the caller's dict (dispatch
    mutates it with __blob__, handlers may mutate further)."""
    buses = _mk(2)
    seen: list = []
    buses[0].on("m", lambda s, p: seen.append(p))
    try:
        payload = {"keys": [1, 2, 3], "nested": {"a": 1}}
        buses[0].send(0, "m", payload, blob=b"bb")
        _wait(lambda: len(seen) >= 1)
        assert seen[0]["keys"] == [1, 2, 3]
        seen[0]["nested"]["a"] = 99
        assert payload["nested"]["a"] == 1  # caller's dict untouched
        assert "__blob__" not in payload
    finally:
        _close(buses)


def test_loopback_interleaves_fifo_with_ring_frames():
    """Self frames and ring frames both dispatch on the one recv
    thread; the self lane keeps ITS OWN order (cross-lane order is
    unspecified, like any two senders)."""
    buses = _mk(2)
    got: list = []
    buses[0].on("y", lambda s, p: got.append((s, p["i"])))
    try:
        for i in range(100):
            buses[1].send(0, "y", {"i": i})
            buses[0].send(0, "y", {"i": i})
        _wait(lambda: len(got) >= 200)
        from_self = [i for s, i in got if s == 0]
        from_peer = [i for s, i in got if s == 1]
        assert from_self == list(range(100))
        assert from_peer == list(range(100))
    finally:
        _close(buses)


def test_loopback_post_close_is_noop_and_zmq_still_refuses():
    buses = _mk(2)
    try:
        buses[0].close()
        buses[0].send(0, "x", {"i": 1})  # silent no-op, like publish
    finally:
        _close(buses)
    # the zmq/native backends keep refusing self-sends: only the shm
    # backend advertises the capability the serve plane probes
    zbuses = mk_loopback_buses(2)
    try:
        assert not getattr(zbuses[0], "supports_loopback", False)
        with pytest.raises(ValueError, match="self"):
            zbuses[0].send(0, "x", {})
    finally:
        for b in zbuses:
            b.close()
