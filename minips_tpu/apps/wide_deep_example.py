"""wide_deep_example — Wide&Deep / DeepFM CTR on Criteo-shaped data
(BASELINE.json:10: "Wide&Deep / DeepFM on Criteo-1TB, sparse embedding PS
shards on TPU mesh"). The flagship workload: hashed wide weights (dim 1) +
hashed field embeddings + a dense deep tower, all in one fused SPMD step.

Usage: python -m minips_tpu.apps.wide_deep_example --model deepfm
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from minips_tpu.apps.common import app_main, holdout_split, score_holdout
from minips_tpu.core.config import Config, TableConfig, TrainConfig
from minips_tpu.data.loader import BatchIterator
from minips_tpu.data import synthetic
from minips_tpu.models import wide_deep as wd_model
from minips_tpu.parallel.mesh import make_mesh
from minips_tpu.tables.dense import DenseTable
from minips_tpu.tables.sparse import SparseTable
from minips_tpu.train.loop import TrainLoop
from minips_tpu.train.ps_step import PSTrainStep

DEFAULT = Config(
    table=TableConfig(name="ctr", kind="sparse", consistency="bsp",
                      updater="adagrad", lr=0.05, dim=8,
                      num_slots=1 << 18),
    train=TrainConfig(batch_size=1024, num_iters=200),
)
NUM_DENSE, NUM_CAT = 13, 26


def build(cfg: Config, *, use_fm: bool, mesh=None, seed: int = 0,
          compute_dtype=None):
    """Tables + fused step for W&D/DeepFM; also used by
    __graft_entry__.dryrun_multichip."""
    mesh = mesh or make_mesh()
    emb_dim = cfg.table.dim
    wide_t = SparseTable(cfg.table.num_slots, 1, mesh, name="wide",
                         updater=cfg.table.updater, lr=cfg.table.lr,
                         init_scale=0.0, salt=1, seed=seed)
    emb_t = SparseTable(cfg.table.num_slots, emb_dim, mesh, name="emb",
                        updater=cfg.table.updater, lr=cfg.table.lr,
                        init_scale=0.01, salt=2, seed=seed + 1)
    deep_t = DenseTable(
        wd_model.init_deep(jax.random.PRNGKey(seed + 2), NUM_CAT, emb_dim,
                           NUM_DENSE),
        mesh, name="deep", updater="adam", lr=1e-3)

    def loss_fn(deep_params, rows, batch):
        return wd_model.loss(rows["wide"], rows["emb"], deep_params, batch,
                             use_fm=use_fm)

    ps = PSTrainStep(loss_fn, dense=deep_t,
                     sparse={"wide": wide_t, "emb": emb_t},
                     key_fns={"wide": lambda b: b["cat"],
                              "emb": lambda b: b["cat"]},
                     compute_dtype=compute_dtype)
    return ps, (wide_t, emb_t, deep_t)


def _run_streaming(cfg: Config, args, metrics, path: str, *,
                   use_fm: bool) -> dict:
    """One-pass streaming training: the Criteo file is NEVER resident —
    a producer thread parses ~4MB chunks while earlier batches train
    (data/criteo.py stream_criteo_batches; the Criteo-1TB posture). The
    loop ends at min(num_iters, file exhaustion). Holdout eval needs
    resident rows, so --eval_frac is rejected loudly here."""
    if getattr(args, "eval_frac", None):
        raise SystemExit("--eval_frac needs resident rows; it is not "
                         "available with --stream (run a separate "
                         "non-stream eval pass)")
    from minips_tpu.data.criteo import log_transform, stream_criteo_batches

    ps, tables = build(cfg, use_fm=use_fm, seed=cfg.train.seed,
                       compute_dtype=(jnp.bfloat16
                                      if getattr(args, "dtype", "float32")
                                      == "bfloat16" else None))

    def xform(d):  # producer-thread preprocessing
        return {"dense": log_transform(d["dense"], d["dense_mask"]),
                "cat": d["cat"], "y": d["y"]}

    stream_stats: dict = {}
    batches = stream_criteo_batches(path, cfg.train.batch_size,
                                    transform=xform, stats=stream_stats)
    loop = TrainLoop(lambda b: ps(ps.shard_batch(b)), batches,
                     metrics=metrics, log_every=cfg.train.log_every,
                     batch_size=cfg.train.batch_size)
    losses = loop.run(cfg.train.num_iters)
    metrics.log(final_loss=losses[-1] if losses else None,
                samples_per_sec=loop.timer.samples_per_sec,
                # no-silent-caps: rows short of one final batch (absent
                # when num_iters ended the loop before EOF)
                stream_dropped_rows=stream_stats.get("dropped_rows"),
                streamed=True)
    return {"losses": losses,
            "samples_per_sec": loop.timer.samples_per_sec,
            "tables": tables}


def _make_predict(wide_t, emb_t, deep_params, use_fm: bool):
    """Holdout scorer over the live tables + a pulled deep snapshot —
    shared by the spmd and threaded paths so their AUC is computed by one
    code path."""
    def predict(b):
        cats = jnp.asarray(b["cat"])
        return wd_model.logits(
            wide_t.pull(cats), emb_t.pull(cats), deep_params,
            {"dense": jnp.asarray(b["dense"])}, use_fm=use_fm)
    return predict


def _log_collisions(metrics, cats, num_slots) -> dict:
    """Measured key→slot collision rate of the hashed tables over this
    run's key stream (sampled) — hash merging is invisible quality loss
    unless logged (VERDICT r2 #5; sizing guidance in docs/api.md). Both
    tables hash the same cat keys under their own salt."""
    from minips_tpu.tables.sparse import collision_stats

    out = {}
    for name, salt in (("wide", 1), ("emb", 2)):
        st = collision_stats(cats, num_slots, salt=salt)
        out[name] = st
        metrics.log(table=name, **{f"collision_{k}": v
                                   for k, v in st.items()})
    return out


def run(cfg: Config, args, metrics) -> dict:
    use_fm = getattr(args, "model", "widedeep") == "deepfm"
    if getattr(args, "stream", False) \
            and getattr(args, "exec_mode", "spmd") != "spmd":
        # loud beats silently dropping either flag (same convention as
        # _run_threaded's --dtype rejection)
        raise SystemExit("--stream is only wired into --exec spmd")
    if getattr(args, "exec_mode", "spmd") == "multiproc":
        return _run_multiproc(cfg, args, metrics, use_fm=use_fm)
    path = getattr(args, "data_file", None)
    if path and getattr(args, "stream", False):
        return _run_streaming(cfg, args, metrics, path, use_fm=use_fm)
    if getattr(args, "stream", False):
        raise SystemExit("--stream needs --data_file (a file to stream)")
    if path:  # real Criteo TSV through the native/python reader
        from minips_tpu.data.criteo import log_transform, read_criteo
        raw = read_criteo(path)
        data = {"dense": log_transform(raw["dense"], raw["dense_mask"]),
                "cat": raw["cat"], "y": raw["y"]}
    else:
        data = synthetic.criteo_like(16384, seed=cfg.train.seed)
    data, holdout = holdout_split(data,
                                  getattr(args, "eval_frac", None) or 0.0,
                                  seed=cfg.train.seed)
    if getattr(args, "exec_mode", "spmd") == "threaded":
        return _run_threaded(cfg, args, metrics, data, holdout,
                             use_fm=use_fm)
    ps, tables = build(cfg, use_fm=use_fm, seed=cfg.train.seed,
                       compute_dtype=(jnp.bfloat16
                                      if getattr(args, "dtype", "float32")
                                      == "bfloat16" else None))
    _log_collisions(metrics, data["cat"], cfg.table.num_slots)
    batches = BatchIterator(data, cfg.train.batch_size, seed=cfg.train.seed)
    loop = TrainLoop(lambda b: ps(ps.shard_batch(b)), batches,
                     metrics=metrics, log_every=cfg.train.log_every,
                     batch_size=cfg.train.batch_size)
    losses = loop.run(cfg.train.num_iters)
    metrics.log(final_loss=losses[-1],
                samples_per_sec=loop.timer.samples_per_sec)
    wide_t, emb_t, deep_t = tables
    return score_holdout(
        _make_predict(wide_t, emb_t, deep_t.pull(), use_fm), holdout,
        {"losses": losses, "samples_per_sec": loop.timer.samples_per_sec,
         "tables": tables}, metrics)


def _run_threaded(cfg: Config, args, metrics, data, holdout, *,
                  use_fm: bool) -> dict:
    """Reference-semantics worker threads for the flagship workload: each
    thread pulls the batch's embedding rows + the deep tower through the
    consistency gate, pushes grads, clocks — the threaded Engine path the
    other apps already have (SURVEY.md §3.3 hot loop, thread-per-worker)."""
    from minips_tpu.consistency import make_controller
    from minips_tpu.core.engine import Engine
    from minips_tpu.apps.common import threaded_train

    if getattr(args, "dtype", "float32") != "float32":
        # loud beats silently training f32 while reporting bf16 (same
        # convention as lm_example's --remat off-dp rejection)
        raise SystemExit("--dtype is only wired into --exec spmd/multiproc")
    _, (wide_t, emb_t, deep_t) = build(cfg, use_fm=use_fm,
                                       seed=cfg.train.seed)
    engine = Engine(num_workers=cfg.train.num_workers).start_everything()
    for name, t in (("wide", wide_t), ("emb", emb_t), ("deep", deep_t)):
        engine.register_table(name, t, make_controller(
            cfg.table.consistency, engine.num_workers,
            staleness=cfg.table.staleness, sync_every=0))

    @jax.jit
    def g(wide_rows, emb_rows, deep_params, batch):
        def f(w, e, dp):
            return wd_model.loss(w, e, dp, batch, use_fm=use_fm)
        loss, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(
            wide_rows, emb_rows, deep_params)
        return (loss,) + grads

    NW = engine.num_workers

    def step_fn(info, batch):
        wt, et, dt = (info.table(n) for n in ("wide", "emb", "deep"))
        cats = jnp.asarray(batch["cat"])
        w_rows = wt.pull(keys=cats)  # [B, NUM_CAT, 1]
        e_rows = et.pull(keys=cats)  # [B, NUM_CAT, dim]
        deep_params = dt.pull()
        loss, gw, ge, gd = g(w_rows, e_rows, deep_params,
                             {"dense": jnp.asarray(batch["dense"]),
                              "y": jnp.asarray(batch["y"])})
        # NW workers each push once per clock; /NW keeps the per-round
        # update magnitude equal to the spmd path's single mean-loss push
        # for EVERY updater (adagrad normalizes constants away, sgd does
        # not — unscaled pushes would be an NW-times effective lr)
        wt.push(gw / NW, keys=cats)
        et.push(ge / NW, keys=cats)
        dt.push(jax.tree.map(lambda x: x / NW, gd))
        return loss

    mean_losses = threaded_train(engine, cfg, data, step_fn,
                                 clock_tables=["wide", "emb", "deep"])
    deep_params = deep_t.pull()
    engine.stop_everything()
    metrics.log(final_loss=mean_losses[-1])
    return score_holdout(
        _make_predict(wide_t, emb_t, deep_params, use_fm), holdout,
        {"losses": mean_losses, "samples_per_sec": 0.0,
         "tables": (wide_t, emb_t, deep_t)}, metrics)


def _run_multiproc(cfg: Config, args, metrics, *, use_fm: bool) -> dict:
    """The flagship sparse workload on the key-range-sharded PS
    (VERDICT r1 #3): N launcher processes, each with its own Criteo data
    shard; wide/emb tables PARTITIONED across processes (per-process
    memory ~1/N), pushes ship only the batch's touched rows per owner —
    row-sparse, never a table-sized blob; the deep tower rides the dense
    range path; BSP/SSP/ASP via the owner-side staleness gate. Prints the
    one-JSON-line launcher protocol (smoke tests / bench)."""
    import os
    import sys
    import time

    import numpy as np

    import jax.numpy as jnp

    from minips_tpu.apps.common import (emit_multiproc_done, holdout_split,
                                        init_multiproc, run_multiproc_body,
                                        shard_checkpointing)
    from minips_tpu.data import synthetic
    from minips_tpu.tables.sparse import hash_to_slots_np
    from minips_tpu.train.sharded_ps import (ShardedTable, ShardedPSTrainer)
    from minips_tpu.utils.evaluation import StreamingAUC, padded_chunks

    rank, nprocs, bus, monitor, staleness = init_multiproc(
        cfg.table.consistency, cfg.table.staleness)

    path = getattr(args, "data_file", None)
    if path:  # real Criteo TSV; round-robin row shard per rank
        from minips_tpu.data.criteo import log_transform, read_criteo
        raw = read_criteo(path)
        data = {"dense": log_transform(raw["dense"], raw["dense_mask"]),
                "cat": raw["cat"], "y": raw["y"]}
        data = {k: v[rank::nprocs] for k, v in data.items()}
    else:  # per-rank synthetic shard (disjoint seeds, shared signal)
        data = synthetic.criteo_like(8192, seed=100 + rank)
    # explicit --eval_frac 0 disables eval (the flag's contract); only an
    # UNSET flag takes the multiproc default of 0.2
    frac = getattr(args, "eval_frac", None)
    frac = 0.2 if frac is None else frac
    data, holdout = holdout_split(data, frac, seed=cfg.train.seed)

    slots = cfg.table.num_slots
    emb_dim = cfg.table.dim
    # per-rank measured collision accounting for the hashed tables (the
    # multiproc twin of _log_collisions; same salts)
    coll = _log_collisions(metrics, data["cat"], slots)
    updater = cfg.table.updater  # sgd/adagrad/adam all server-side now
    push_comm = getattr(args, "push_comm", "float32")
    mk = lambda name, dim, scale, seed, comm="float32": ShardedTable(  # noqa: E731
        name, slots, dim, bus, rank, nprocs, updater=updater,
        lr=cfg.table.lr, init_scale=scale, seed=seed, monitor=monitor,
        pull_timeout=30.0, push_comm=comm)
    # --push-comm compresses only wide-DIMENSION tables (the emb table):
    # at dim 1 (wide_t) the per-row f32 scale outweighs the int8 saving
    wide_t = mk("wide", 1, 0.0, 1)
    emb_t = mk("emb", emb_dim, 0.01, 2, comm=push_comm)
    # deep tower: flat param vector on the dense range path (adagrad
    # server-side — the reference's dense-updater family)
    import jax
    from jax.flatten_util import ravel_pytree
    deep0 = wd_model.init_deep(jax.random.PRNGKey(cfg.train.seed + 2),
                               NUM_CAT, emb_dim, NUM_DENSE)
    deep_flat0, unravel = ravel_pytree(deep0)
    deep_t = ShardedTable("deep", deep_flat0.shape[0], 1, bus, rank, nprocs,
                          updater="adagrad", lr=0.02, monitor=monitor,
                          pull_timeout=30.0)
    trainer = ShardedPSTrainer(
        {"wide": wide_t, "emb": emb_t, "deep": deep_t}, bus, nprocs,
        staleness=staleness, gate_timeout=30.0, monitor=monitor)
    resume = shard_checkpointing(bus, nprocs, cfg.train.checkpoint_dir,
                                 rank)
    bus.handshake(nprocs)
    # the deep table stores the DELTA from a shared deterministic init
    # (every rank derives deep_flat0 from the same PRNGKey): the zero
    # table needs no init broadcast, and range pushes stay pure grads
    start_iter, save_hook = resume(
        {"wide": wide_t, "emb": emb_t, "deep": deep_t, "trainer": trainer},
        cfg.train.checkpoint_every)

    @jax.jit
    def wd_grads(wide_rows, emb_rows, deep_vec, batch):
        def f(w, e, dv):
            return wd_model.loss(w, e, unravel(dv[:, 0] + deep_flat0),
                                 batch, use_fm=use_fm)
        loss, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(
            wide_rows, emb_rows, deep_vec)
        return (loss,) + grads

    B = cfg.train.batch_size
    # resumed runs reseed on (rank, start): sampling is with-replacement,
    # so resume is convergence-equivalent, not bit-exact
    rng = np.random.default_rng((rank, start_iter))
    losses = []
    auc_val = None
    fp = 0.0
    t0 = time.monotonic()

    def body():
        nonlocal auc_val, fp
        for i in range(start_iter, cfg.train.num_iters):
            kill_at = getattr(args, "kill_at", 0)
            if kill_at and rank == getattr(args, "kill_rank", -1) \
                    and i == kill_at:
                os._exit(137)
            sel = rng.integers(0, data["y"].shape[0], size=B)
            cats = data["cat"][sel]
            wide_keys = hash_to_slots_np(cats, slots, 1).reshape(-1)
            emb_keys = hash_to_slots_np(cats, slots, 2).reshape(-1)
            wide_rows = wide_t.pull(wide_keys).reshape(B, NUM_CAT, 1)
            emb_rows = emb_t.pull(emb_keys).reshape(B, NUM_CAT, emb_dim)
            deep_vec = deep_t.pull_all()
            loss, gw, ge, gd = wd_grads(
                jnp.asarray(wide_rows), jnp.asarray(emb_rows),
                jnp.asarray(deep_vec),
                {"dense": jnp.asarray(data["dense"][sel]),
                 "y": jnp.asarray(data["y"][sel])})
            wide_t.push(wide_keys, np.asarray(gw).reshape(-1, 1))
            emb_t.push(emb_keys, np.asarray(ge).reshape(-1, emb_dim))
            deep_t.push_dense(np.asarray(gd))
            losses.append(float(loss))
            trainer.tick()
            save_hook(i)
            slow_rank = getattr(args, "slow_rank", -1)
            if rank == slow_rank and getattr(args, "slow_ms", 0) > 0:
                time.sleep(args.slow_ms / 1000.0)
        trainer.finalize(timeout=30.0)
        # ---- streaming holdout AUC on the FINAL shared tables
        if holdout is not None:
            auc = StreamingAUC()
            deep_final = unravel(deep_t.pull_all()[:, 0] + deep_flat0)
            for chunk, n_valid in padded_chunks(holdout, 4096):
                cats = chunk["cat"]
                cb = cats.shape[0]
                w_rows = wide_t.pull(
                    hash_to_slots_np(cats, slots, 1).reshape(-1)
                ).reshape(cb, NUM_CAT, 1)
                e_rows = emb_t.pull(
                    hash_to_slots_np(cats, slots, 2).reshape(-1)
                ).reshape(cb, NUM_CAT, emb_dim)
                lg = wd_model.logits(
                    jnp.asarray(w_rows), jnp.asarray(e_rows), deep_final,
                    {"dense": jnp.asarray(chunk["dense"])}, use_fm=use_fm)
                auc.update(np.asarray(lg)[:n_valid], chunk["y"][:n_valid])
            auc_val = auc.result()
        # fingerprints for the replica-agreement assertion
        fp = (float(np.sum(wide_t.pull_all()))
              + float(np.sum(emb_t.pull_all()))
              + float(np.sum(deep_t.pull_all())))
        trainer.shutdown_barrier(timeout=10.0)

    code = run_multiproc_body(rank, trainer, body)
    if code == 0:
        from minips_tpu.train.sharded_ps import table_state_bytes
        # deep table is always adagrad server-side (shard + accumulator)
        table_bytes = (table_state_bytes(slots, 1, updater)        # wide
                       + table_state_bytes(slots, emb_dim, updater)  # emb
                       + table_state_bytes(deep_flat0.shape[0], 1,
                                           "adagrad"))             # deep
        # metrics BEFORE the protocol line: the launcher harvests the LAST
        # JSON line on stdout as the result dict
        metrics.log(final_loss=losses[-1] if losses else None,
                    holdout_auc=auc_val)
        emit_multiproc_done(
            trainer, rank, t0, losses, table_bytes, fp,
            auc=auc_val, resumed_from=start_iter,
            push_comm=push_comm,
            emb_collision_rate=coll["emb"]["collision_rate"],
            emb_unique_keys=coll["emb"]["unique_keys"],
            # embedding-table wire alone: the row-sparse claim is about
            # these (the deep tower is inherently dense-range traffic)
            sparse_bytes_pushed=wide_t.bytes_pushed + emb_t.bytes_pushed,
            emb_bytes_pushed=emb_t.bytes_pushed)
    monitor.stop()
    bus.close()
    if code:
        sys.exit(code)
    return {"losses": losses, "auc": auc_val}


def _flags(parser):
    parser.add_argument("--model", default="widedeep",
                        choices=["widedeep", "deepfm"])
    parser.add_argument("--data_file", default=None,
                        help="Criteo TSV file instead of synthetic data")
    parser.add_argument("--stream", action="store_true",
                        help="one-pass streaming read of --data_file: a "
                             "producer thread parses chunks while training "
                             "runs; the file is never resident (Criteo-1TB "
                             "posture). Ends at min(num_iters, EOF)")
    parser.add_argument("--dtype", default="float32",
                        choices=["float32", "bfloat16"],
                        help="worker-math precision (master tables stay "
                             "float32)")
    parser.add_argument("--eval_frac", type=float, default=None,
                        help="fraction of rows held out and scored by "
                             "streaming ROC-AUC after training; 0 disables "
                             "(default: 0 for spmd/threaded, 0.2 for "
                             "multiproc)")
    from minips_tpu.apps.common import add_push_comm_flag

    add_push_comm_flag(parser)
    # multiproc straggler/fault injection (smoke tests)
    parser.add_argument("--slow-rank", dest="slow_rank", type=int,
                        default=-1)
    parser.add_argument("--slow-ms", dest="slow_ms", type=float,
                        default=0.0)
    parser.add_argument("--kill-at", dest="kill_at", type=int, default=0)
    parser.add_argument("--kill-rank", dest="kill_rank", type=int,
                        default=-1)


def main():
    return app_main("wide_deep_example", DEFAULT, run, extra_flags=_flags,
                    exec_choices=("spmd", "threaded", "multiproc"))


if __name__ == "__main__":
    main()
