"""CollectiveSSPPS — the consistency axis over the flagship DeepFM and
LM workloads (VERDICT r4 next #2): row-sparse collective merges for the
hashed SparseTables, dense vector merges for the deep tower / the
transformer, with the batch-sized-traffic invariant asserted.

Fast tier: single-process exactness (a 1-process sync must be an exact
no-op, so the CSSP trajectory is bitwise the raw fused-step trajectory),
BlobExchange unit behavior, and the union-merge row accounting. Slow
tier: 2-real-process launcher smokes with skew bound + replica agreement
+ union-sized sync proof.
"""

import sys
import time

import numpy as np
import pytest

from minips_tpu import launch

APP = "minips_tpu.apps.multihost_example"


def _run_multihost(n, extra, *, local_devices=2, timeout=300.0):
    return launch.run_local_job(
        n, [sys.executable, "-m", APP] + extra,
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1",
                   "MINIPS_MH_LOCAL_DEVICES": str(local_devices)},
        timeout=timeout)


# ------------------------------------------------------------- fast tier
def _tiny_build(mesh, updater="adagrad", num_slots=4096, seed=0):
    from minips_tpu.apps.wide_deep_example import build
    from minips_tpu.core.config import Config, TableConfig, TrainConfig

    cfg = Config(
        table=TableConfig(name="ctr", kind="sparse", updater=updater,
                          lr=0.05, dim=4, num_slots=num_slots),
        train=TrainConfig(batch_size=32, num_iters=4),
    )
    ps, (wide_t, emb_t, deep_t) = build(cfg, use_fm=True, mesh=mesh,
                                        seed=seed)
    return ps, {"wide": wide_t, "emb": emb_t, "deep": deep_t}


def _batches(n, bsz=32, seed=0):
    from minips_tpu.data import synthetic

    data = synthetic.criteo_like(1024, seed=seed)
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        sel = rng.integers(0, data["y"].shape[0], size=bsz)
        out.append({k: v[sel] for k, v in data.items()})
    return out


@pytest.mark.parametrize("updater", ["sgd", "adagrad", "adam"])
def test_single_process_cssp_is_bitwise_the_fused_step(updater):
    """With one process the merge is ``base + 1·delta`` = the live params
    — CSSP must not perturb the trajectory AT ALL: losses equal the raw
    PSTrainStep run bitwise, for every sparse updater (the merge touches
    emb AND optimizer rows)."""
    from minips_tpu.train.cssp_ps import CollectiveSSPPS

    bs = _batches(6)
    trainer = CollectiveSSPPS(
        lambda m: _tiny_build(m, updater=updater), staleness=2,
        sync_every=2)
    cssp_losses = [trainer.step(b) for b in bs]
    trainer.finalize()

    from minips_tpu.parallel.mesh import make_mesh

    ps, _ = _tiny_build(make_mesh(8), updater=updater)
    raw_losses = [float(ps(ps.shard_batch(b))) for b in bs]
    assert cssp_losses == raw_losses
    assert trainer.sync_rounds == 3


def test_sync_leaves_untouched_rows_and_bases_consistent():
    """After a sync: bases equal the live state (next round's deltas
    start at zero), and rows never touched keep their init values."""
    from minips_tpu.train.cssp_ps import CollectiveSSPPS

    trainer = CollectiveSSPPS(lambda m: _tiny_build(m), sync_every=1)
    emb_t = trainer.sparse["emb"]
    init_emb = np.asarray(emb_t.emb).copy()
    touched: set = set()
    for b in _batches(3):
        trainer.step(b)
        from minips_tpu.tables.sparse import hash_to_slots_np

        touched.update(hash_to_slots_np(
            b["cat"].reshape(-1), emb_t.num_slots, emb_t.salt).tolist())
    for name, t in trainer.sparse.items():
        for lname, leaf in trainer._leaves(t):
            np.testing.assert_array_equal(
                np.asarray(leaf),
                np.asarray(trainer._sparse_base[name][lname]))
    untouched = np.setdiff1d(np.arange(emb_t.num_slots),
                             np.fromiter(touched, dtype=np.int64))
    now = np.asarray(emb_t.emb)
    np.testing.assert_array_equal(now[untouched], init_emb[untouched])
    # and the touched rows DID move
    assert np.abs(now - init_emb).sum() > 0


def test_row_merge_programs_roundtrip():
    """The jitted row-sparse merge programs directly (the multi-process
    arithmetic, runnable without peers): delta gathers fill 0 for the
    out-of-bounds padding sentinel, apply lands ``base + merged`` on
    exactly the union rows (padding DROPS), and bases track the result."""
    import jax
    import jax.numpy as jnp

    from minips_tpu.train.cssp_ps import CollectiveSSPPS

    trainer = CollectiveSSPPS(lambda m: _tiny_build(m, num_slots=64))
    emb_t = trainer.sparse["emb"]
    dim = emb_t.dim
    base = trainer._sparse_base["emb"]["emb"]
    # move three rows locally, one of them twice
    rng = np.random.default_rng(0)
    bump = rng.normal(size=(3, dim)).astype(np.float32)
    emb_t.emb = emb_t.emb.at[jnp.array([3, 9, 40])].add(jnp.asarray(bump))
    idx = np.full(8, emb_t.num_slots, np.int64)   # C=8, union size 3
    idx[:3] = [3, 9, 40]
    idxd = jax.device_put(jnp.asarray(idx, jnp.int32),
                          trainer._rep_sharding)
    delta = trainer._rows_delta(emb_t.emb, base, idxd)
    d = np.asarray(delta).reshape(8, dim)
    np.testing.assert_allclose(d[:3], bump, rtol=1e-6)
    np.testing.assert_array_equal(d[3:], 0.0)     # padding gathers zero
    # simulate the psum result of 2 procs (mine twice) and apply
    merged = jax.device_put(delta * 2.0, delta.sharding)
    new_leaf, new_base = trainer._apply_for(emb_t.emb.sharding)(
        emb_t.emb, base, idxd, merged)
    out = np.asarray(new_leaf)
    np.testing.assert_allclose(out[[3, 9, 40]],
                               np.asarray(base)[[3, 9, 40]] + 2.0 * bump,
                               rtol=1e-6)
    untouched = np.setdiff1d(np.arange(64), [3, 9, 40])
    np.testing.assert_array_equal(out[untouched],
                                  np.asarray(emb_t.emb)[untouched])
    np.testing.assert_array_equal(np.asarray(new_base), out)


def test_sync_block_rows_divisible_for_any_device_count():
    """Regression for the shard_map divisibility bug: the padded union
    block C was ``max(next_pow2(union), n_local)``, which a
    non-power-of-two device count divides only by luck (n_local=6,
    union=5 gave C=8 → 8 % 6 != 0 and the sharded merge aborts). The
    fixed ``sync_block_rows`` must cover the union AND divide evenly."""
    from minips_tpu.tables.sparse import next_pow2
    from minips_tpu.train.cssp_ps import sync_block_rows

    for n_local in (1, 2, 3, 4, 6, 8, 12):
        for union in (1, 2, 5, 6, 7, 31, 100):
            c = sync_block_rows(union, n_local)
            assert c >= union
            assert c % n_local == 0, (union, n_local, c)
            # never smaller than the old retrace-friendly floor
            assert c >= max(next_pow2(union), n_local)
    # the exact case from the bug report: 6 local devices, union of 5
    assert max(next_pow2(5), 6) % 6 != 0      # old formula: broken
    assert sync_block_rows(5, 6) == 12        # fixed: 2 rows/device


def test_sync_block_rows_six_device_mesh_shards_evenly():
    """The same property on a REAL fake-6-device mesh: run the jitted
    rows_delta program with the CSSP vector sharding on a host forced to
    6 CPU devices and require every device to hold an equal shard of the
    C*dim delta (the old C=8, dim=4 block split 32 elements over 6
    devices unevenly; shard_map refuses exactly that layout)."""
    script = r"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from minips_tpu.parallel.mesh import DATA_AXIS
from minips_tpu.train.cssp_ps import sync_block_rows

devs = jax.devices()
assert len(devs) == 6, f"expected 6 fake devices, got {len(devs)}"
mesh = Mesh(np.asarray(devs), (DATA_AXIS,))
vec_sharding = NamedSharding(mesh, P(DATA_AXIS))

dim, union, n_local = 4, 5, len(devs)
C = sync_block_rows(union, n_local)
assert C % n_local == 0, (C, n_local)

def rows_delta(cur, base, idx):
    d = (cur.at[idx].get(mode="fill", fill_value=0)
         - base.at[idx].get(mode="fill", fill_value=0))
    return d.reshape(-1)

cur = jnp.arange(16 * dim, dtype=jnp.float32).reshape(16, dim)
base = jnp.zeros_like(cur)
idx = np.full(C, 16, np.int64)        # out-of-bounds padding sentinel
idx[:union] = np.arange(union)
out = jax.jit(rows_delta, out_shardings=vec_sharding)(
    cur, base, jnp.asarray(idx, jnp.int32))
shapes = {s.data.shape for s in out.addressable_shards}
assert shapes == {(C * dim // n_local,)}, shapes
print("SIX_DEV_OK", C)
"""
    import os
    import pathlib
    import subprocess

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=6"
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=300,
                          cwd=repo, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "SIX_DEV_OK 12" in proc.stdout


def test_blob_exchange_allgather_and_early_arrival():
    """BlobExchange: both directions deliver, order is by rank, and an
    early round-r+1 arrival parks until that round is consumed."""
    from tests.test_comm import _mk_buses

    from minips_tpu.comm.bus import BlobExchange

    buses = _mk_buses(2)
    try:
        ex0, ex1 = (BlobExchange(buses[0], 2), BlobExchange(buses[1], 2))
        a0 = np.array([3, 1, 2], np.int64)
        a1 = np.array([7, 1], np.int64)
        b1 = np.array([9], np.int64)
        # bus 1 publishes rounds 0 AND 1 before bus 0 starts round 0
        import threading

        res1 = {}

        def side1():
            res1["r0"] = ex1.allgather(0, "emb", a1, timeout=20)
            res1["r1"] = ex1.allgather(1, "emb", b1, timeout=20)

        th = threading.Thread(target=side1)
        th.start()
        time.sleep(0.3)
        got0 = ex0.allgather(0, "emb", a0, timeout=20)
        np.testing.assert_array_equal(got0[0], a0)
        np.testing.assert_array_equal(got0[1], a1)
        got0b = ex0.allgather(1, "emb", np.array([], np.int64), timeout=20)
        np.testing.assert_array_equal(got0b[1], b1)
        th.join(timeout=20)
        np.testing.assert_array_equal(res1["r0"][0], a0)
        np.testing.assert_array_equal(res1["r1"][1], b1)
    finally:
        for b in buses:
            b.close()


def test_cssp_ps_refuses_foreign_tables_and_busless_multiproc(monkeypatch):
    import jax

    from minips_tpu.train.cssp_ps import CollectiveSSPPS

    with pytest.raises(TypeError, match="syncs DenseTable"):
        def bad_build(mesh):
            ps, tables = _tiny_build(mesh)
            tables["oops"] = object()
            return ps, tables
        CollectiveSSPPS(bad_build)

    # multi-process without the bus must refuse LOUDLY: the union
    # exchange has no other transport, and running without it would be
    # the consistency contract silently not enforced
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="control bus"):
        CollectiveSSPPS(_tiny_build, bus=None)


def test_adam_moment_reconciliation_vs_centralized():
    """VERDICT r4 next #3: adam under CollectiveSSP is NOT centralized
    server-side adam — pin how far it diverges. 2 simulated islands
    (disjoint submeshes, the oracle's merge schedule) vs ONE table whose
    shared adam state sees every island's push — the reference's server
    semantics (train/sharded_ps.py holds state that way). Measured at
    these shapes: both opt_sync modes land ~11% of ||central|| away from
    the centralized params (ratio avg/local ≈ 1.01 — averaging moments
    does NOT buy distance-to-centralized at smoke scale; its benefit is
    that replica moments are bitwise IDENTICAL after every merge, so the
    inter-replica moment drift is bounded instead of unbounded — both
    facts asserted here and stated in docs/consistency.md)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from minips_tpu.models import lr as lr_model
    from minips_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from minips_tpu.tables.dense import DenseTable

    D, B, iters, sync_every = 32, 64, 24, 4
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=D)
    xs, ys = [], []
    for _ in range(iters):
        x = rng.normal(size=(B, D)).astype(np.float32)
        xs.append(x)
        ys.append((x @ w_true > 0).astype(np.float32))
    devs = jax.devices()

    def island_run(opt_sync):
        copy = jax.jit(jnp.copy)
        tables, steps, bases = [], [], []
        for h in range(2):
            mesh = Mesh(np.asarray(devs[h * 4:(h + 1) * 4]), (DATA_AXIS,))
            t = DenseTable(lr_model.init(D), mesh, name=f"i{h}{opt_sync}",
                           updater="adam", lr=0.02)
            tables.append(t)
            steps.append(t.make_step(lr_model.grad_fn_dense))
            bases.append(copy(t.params))
        for i in range(iters):
            for h in range(2):
                sh = NamedSharding(tables[h].mesh, P(DATA_AXIS))
                half = slice(h * B // 2, (h + 1) * B // 2)
                tables[h].step_inplace(steps[h], {
                    "x": jax.device_put(xs[i][half], sh),
                    "y": jax.device_put(ys[i][half], sh)})
            if (i + 1) % sync_every == 0 or i + 1 == iters:
                deltas = [np.asarray(t.params) - np.asarray(b)
                          for t, b in zip(tables, bases)]
                total = np.sum(deltas, axis=0)
                for h in range(2):
                    merged = jnp.asarray(np.asarray(bases[h]) + total)
                    tables[h].params = jax.device_put(
                        merged, tables[h].params.sharding)
                    bases[h] = copy(tables[h].params)
                if opt_sync == "avg":   # avg_table_opt_state's rule
                    from minips_tpu.train.ssp_spmd import is_avg_leaf

                    flats = [jax.tree.flatten(t.opt_state)
                             for t in tables]
                    for j, leaf in enumerate(flats[0][0]):
                        if not is_avg_leaf(leaf, tables[0].padded):
                            continue
                        mean = np.mean(
                            [np.asarray(f[0][j], np.float32)
                             for f in flats], axis=0).astype(leaf.dtype)
                        for h in range(2):
                            lv, td = jax.tree.flatten(tables[h].opt_state)
                            lv[j] = jax.device_put(jnp.asarray(mean),
                                                   lv[j].sharding)
                            tables[h].opt_state = jax.tree.unflatten(
                                td, lv)
        if opt_sync == "avg":
            # the reconciliation's actual guarantee: replica moments are
            # IDENTICAL after the final merge (local lets them walk)
            for a, b in zip(jax.tree.leaves(tables[0].opt_state),
                            jax.tree.leaves(tables[1].opt_state)):
                if (getattr(a, "ndim", None) == 1
                        and a.shape[0] == tables[0].padded):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))
        return np.asarray(tables[0].params)[: tables[0].num_keys]

    # centralized: one shared adam state, one push per island per step
    ct = DenseTable(lr_model.init(D), make_mesh(8), name="central",
                    updater="adam", lr=0.02)
    for i in range(iters):
        for h in range(2):
            half = slice(h * B // 2, (h + 1) * B // 2)
            _, g = lr_model.grad_fn_dense(
                ct.pull(), {"x": jnp.asarray(xs[i][half]),
                            "y": jnp.asarray(ys[i][half])})
            ct.push(g)
    central = np.asarray(ct.params)[: ct.num_keys]

    d_local = float(np.linalg.norm(island_run("local") - central))
    d_avg = float(np.linalg.norm(island_run("avg") - central))
    assert d_local > 0          # the drift is REAL — documented, not hidden
    # avg must stay COMPARABLE to local (measured ratio ~1.01; a
    # regression that makes averaging actively harmful shows up here)
    assert d_avg <= d_local * 1.1, (d_avg, d_local)
    # neither walks out of centralized's neighborhood at this scale
    assert d_avg < 0.5 * np.linalg.norm(central) + 1.0, d_avg


@pytest.mark.parametrize("comm", ["bfloat16", "int8"])
def test_sync_comm_compressed_wire_tolerance(comm):
    """VERDICT r4 next #5: the CollectiveSSP delta merge with a
    compressed wire + error-feedback residual. Same data stream as the
    f32 run: the compressed trajectory must converge to the same
    neighborhood (EF keeps the bias from accumulating), the residual
    must actually be engaged (nonzero — compression IS lossy, EF is
    what makes it safe), and the compiled sync program must carry the
    compressed dtype on its wire collectives."""
    from minips_tpu.models import lr as lr_model
    from minips_tpu.train.ssp_spmd import CollectiveSSP

    D = 64
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=D)
    bs = []
    for _ in range(30):
        x = rng.normal(size=(64, D)).astype(np.float32)
        bs.append({"x": x, "y": (x @ w_true > 0).astype(np.float32)})

    def run(sync_comm):
        tr = CollectiveSSP(lr_model.init(D), lr_model.grad_fn_dense,
                           updater="adagrad", lr=0.3, sync_every=2,
                           sync_comm=sync_comm, name=f"q{sync_comm}")
        ls = [tr.step(b) for b in bs]
        tr.finalize()
        return ls, tr

    f32_ls, _ = run("float32")
    q_ls, tr = run(comm)
    assert q_ls[-1] < q_ls[0] * 0.5             # converges
    assert abs(q_ls[-1] - f32_ls[-1]) < 0.02    # lands by the f32 run
    assert float(np.abs(np.asarray(tr._residual)).sum()) > 0
    # (wire-dtype HLO assertions live in the 2-process slow smoke — on a
    # 1-process plane the all-to-all/all-gather compile away entirely)


def test_sync_comm_refusals():
    """sync_comm composes honestly or not at all: opt_sync='avg' would
    ride the full-precision plane next to a compressed delta (half-
    measure → refuse); unknown formats refuse via the shared comm
    check."""
    from minips_tpu.models import lr as lr_model
    from minips_tpu.train.ssp_spmd import CollectiveSSP

    with pytest.raises(ValueError, match="one lever per run"):
        CollectiveSSP(lr_model.init(16), lr_model.grad_fn_dense,
                      updater="adam", opt_sync="avg", sync_comm="int8")
    with pytest.raises(ValueError, match="comm must be"):
        CollectiveSSP(lr_model.init(16), lr_model.grad_fn_dense,
                      sync_comm="int4")


def test_opt_sync_avg_refuses_adam8():
    from minips_tpu.train.ssp_spmd import CollectiveSSP

    from minips_tpu.models import lr as lr_model

    with pytest.raises(ValueError, match="quantized moments"):
        CollectiveSSP(lr_model.init(64), lr_model.grad_fn_dense,
                      updater="adam8", opt_sync="avg")


# ------------------------------------------------------------- slow tier
@pytest.mark.slow
def test_wd_collective_ssp_two_process():
    """VERDICT r4 next #2 as written: the flagship sparse workload on the
    collective-sync consistency plane — 2 real processes, straggler on
    rank 1, staleness 2, merge every 4 steps. Asserts the skew bound,
    the fast rank's gate engagement, post-finalize replica agreement,
    an all-reduce in the compiled merge, and the batch-sized-traffic
    invariant: the row merge is union-sized (< slots/4 at these shapes),
    and the host-wire union exchange actually carried ids."""
    res = _run_multihost(
        2, ["--model", "wd", "--mode", "ssp", "--staleness", "1",
            "--sync-every", "4", "--iters", "8", "--batch", "64",
            "--num-slots", "65536", "--slow-rank", "1", "--slow-ms",
            "150"])
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done" and r["multi"] is True
        assert r["max_skew_seen"] <= 2, r  # s + 1
        assert r["loss_last"] < r["loss_first"], r
        assert r["sync_rounds"] == 2
        assert r["sync_hlo_has_all_reduce"] is True
        assert 0 < r["sync_rows_max"] < r["num_slots"] // 4, r
        assert r["union_wire_bytes"] > 0, r
        assert r["sync_plane_devices"] == 4
    fast = res[0] if res[0]["rank"] == 0 else res[1]
    assert fast["gate_waits"] > 0, fast
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]


@pytest.mark.slow
def test_wd_collective_bsp_lockstep():
    """The strict end of the axis on the wd workload: bsp holds skew <= 1
    with one merge per step and identical replicas."""
    res = _run_multihost(
        2, ["--model", "wd", "--mode", "bsp", "--iters", "4",
            "--batch", "64", "--num-slots", "65536"])
    for r in res:
        assert r["event"] == "done"
        assert r["max_skew_seen"] <= 1
        assert r["sync_rounds"] == 4
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]


@pytest.mark.slow
def test_wd_collective_asp_never_blocks():
    """The loose end of the axis on the wd workload: asp's gate never
    blocks (gate_waits == 0 on every rank, straggler included) while the
    sync rendezvous still bounds drift — replicas agree after finalize."""
    res = _run_multihost(
        2, ["--model", "wd", "--mode", "asp", "--sync-every", "2",
            "--iters", "4", "--batch", "64", "--num-slots", "65536",
            "--slow-rank", "1", "--slow-ms", "20"])
    for r in res:
        assert r["event"] == "done"
        assert r["gate_waits"] == 0, r
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]


@pytest.mark.slow
def test_multihost_clean_exit_is_rc_zero_repeatedly():
    """Regression pin for the jax.distributed teardown race: before
    cluster.shutdown() (barrier + explicit coordination-service
    disconnect, routed through multihost_example._finish), a COMPLETED
    follower rank was fatally terminated by its error-polling thread
    whenever the coordinator won the exit race — a clean run reported
    rc!=0 roughly half the time once spawn got fast. Three consecutive
    clean jobs through run_local_job (which raises on rc!=0) keep the
    protocol honest; the wd model dispatches the most distinct
    collective programs, making it the raciest exit."""
    for i in range(3):
        res = _run_multihost(
            2, ["--model", "wd", "--mode", "bsp", "--iters", "2",
                "--batch", "64", "--num-slots", "65536"])
        assert all(r["event"] == "done" for r in res), (i, res)


def test_snapshot_schedule_refuses_off_boundary():
    """The sync-boundary snapshot invariant, unit-level (the launcher
    drill covers the happy path; the refusals are pure schedule logic —
    ssp_spmd.validate_snapshot_schedule): off-boundary --save-at /
    --restore-from refuse, iters below one sync window refuse, save
    without a dir refuses, and the default save step rounds DOWN to the
    last boundary."""
    from minips_tpu.train.ssp_spmd import validate_snapshot_schedule

    # off-boundary save and restore refuse loudly
    with pytest.raises(SystemExit, match="not a sync boundary"):
        validate_snapshot_schedule("/tmp/ck", 3, 0, iters=16, sync_every=4)
    with pytest.raises(SystemExit, match="not a sync boundary"):
        validate_snapshot_schedule("/tmp/ck", 0, 6, iters=16, sync_every=4)
    # a job too short to ever sync has nothing coherent to snapshot
    with pytest.raises(SystemExit, match="no sync boundary"):
        validate_snapshot_schedule("/tmp/ck", 0, 0, iters=3, sync_every=8)
    # snapshot flags without a directory refuse
    with pytest.raises(SystemExit, match="need --checkpoint-dir"):
        validate_snapshot_schedule(None, 8, 0, iters=16, sync_every=4)
    # default (--save-at 0) resolves to the LAST boundary, rounded down
    assert validate_snapshot_schedule(
        "/tmp/ck", 0, 0, iters=14, sync_every=4) == 12
    # explicit boundary-aligned values pass through unchanged
    assert validate_snapshot_schedule(
        "/tmp/ck", 8, 4, iters=16, sync_every=4) == 8


@pytest.mark.slow
def test_opt_sync_avg_real_processes_match_oracle():
    """The REAL 2-process opt_sync='avg' run must reproduce the
    sequential 2-virtual-host oracle's loss streams (the oracle's merge
    block implements the same f32-accumulate moment averaging) — the
    implementation equals its spec, adam moments included."""
    import json
    import subprocess

    res = _run_multihost(
        2, ["--mode", "bsp", "--updater", "adam", "--lr", "0.05",
            "--opt-sync", "avg", "--sync-every", "2", "--iters", "8",
            "--batch", "64"], local_devices=4)
    for r in res:
        assert r["event"] == "done" and r["opt_sync"] == "avg"
        assert r["loss_last"] < r["loss_first"], r
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]

    proc = subprocess.run(
        [sys.executable, "-m", APP, "--mode", "bsp", "--updater", "adam",
         "--lr", "0.05", "--opt-sync", "avg", "--sync-every", "2",
         "--iters", "8", "--batch", "64", "--oracle-hosts", "2"],
        capture_output=True, text=True, timeout=240,
        env={**__import__("os").environ, "MINIPS_FORCE_CPU": "1",
             "MINIPS_MH_LOCAL_DEVICES": "8"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    oracle = json.loads([ln for ln in proc.stdout.splitlines()
                         if ln.startswith("{")][-1])
    for r in res:
        np.testing.assert_allclose(
            r["losses"], oracle["losses_per_host"][r["rank"]], rtol=1e-6)


@pytest.mark.slow
def test_collective_ssp_kill_detect_relaunch_resume(tmp_path):
    """VERDICT r4 next #4: the fault drill on the collective-SSP path.
    CollectiveSSP's failure surface is worse than the fused path's — a
    peer dying inside the psum rendezvous leaves survivors BLOCKED in
    XLA, and the gate's monitor hook only covers the host-side wait —
    so detection must ride the watchdog's own thread. Drill: rank 1 dies
    mid-run under --mode ssp; the survivor emits peer_failure and exits
    42 within the heartbeat timeout; relaunch restores the sync-boundary
    snapshot WITH the clock vector, and the resumed trajectory equals
    the uninterrupted run's tail (same sync schedule, same math)."""
    import json

    ck = str(tmp_path / "ck")
    common = ["--mode", "ssp", "--staleness", "2", "--sync-every", "2",
              "--iters", "10", "--batch", "64", "--updater", "adam",
              "--lr", "0.05"]
    # leg 0: the uninterrupted oracle run (same flags, no kill)
    ref = _run_multihost(2, list(common), local_devices=2)
    assert all(r["event"] == "done" for r in ref)

    # leg 1: save at the step-4 sync boundary, rank 1 dies at step 7
    rc, events = launch.run_local_job_raw(
        2, [sys.executable, "-m", APP] + common + [
            "--checkpoint-dir", ck, "--save-at", "4",
            "--kill-at", "7", "--kill-rank", "1"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1",
                   "MINIPS_MH_LOCAL_DEVICES": "2"},
        timeout=300.0)
    assert rc != 0
    surv = [e for e in events[0] if e.get("event") == "peer_failure"]
    assert surv and 1 in surv[0]["dead"], events[0][-3:]

    # leg 2: relaunch, restore step 4 — clock vector restarts there
    res = _run_multihost(
        2, list(common) + ["--checkpoint-dir", ck,
                           "--restore-from", "4"], local_devices=2)
    for r in res:
        assert r["event"] == "done" and r["resumed_from"] == 4
        assert len(r["losses"]) == 6            # iters 4..9
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]
    # trajectory continuation: the resumed tail equals the uninterrupted
    # run's steps 4..9 (the snapshot is a sync boundary, so state AND
    # clocks are exactly the uninterrupted run's at that point)
    for r in res:
        ref_rank = ref[0] if ref[0]["rank"] == r["rank"] else ref[1]
        np.testing.assert_allclose(r["losses"], ref_rank["losses"][4:],
                                   rtol=1e-6)


@pytest.mark.slow
def test_sync_comm_int8_two_process_replicas_identical():
    """The compressed sync wire on real processes: the gather leg means
    every replica dequantizes the SAME compressed chunks, so post-
    finalize fingerprints must still be bitwise EQUAL — compression
    changes the trajectory (within EF-bounded tolerance), never the
    replica agreement. The compiled merge must carry int8 (s8) on
    all-to-all + all-gather wire ops."""
    res = _run_multihost(
        2, ["--mode", "ssp", "--staleness", "2", "--sync-every", "4",
            "--iters", "8", "--batch", "64", "--sync-comm", "int8"])
    for r in res:
        assert r["event"] == "done" and r["sync_comm"] == "int8"
        assert r["loss_last"] < r["loss_first"], r
        assert r["sync_hlo_wire_ok"] is True, r
        assert r["max_skew_seen"] <= 3
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]


@pytest.mark.slow
def test_lm_collective_ssp_two_process():
    """The LM family on the collective consistency axis: per-process DP
    islands over the transformer, dense delta merges, same skew bound
    and replica-agreement observables as the wd leg."""
    res = _run_multihost(
        2, ["--model", "lm", "--mode", "ssp", "--staleness", "2",
            "--sync-every", "4", "--iters", "8", "--batch", "8",
            "--seq-len", "32", "--slow-rank", "1", "--slow-ms", "150",
            "--updater", "adagrad", "--lr", "0.1"])
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done" and r["multi"] is True
        assert r["max_skew_seen"] <= 3, r
        assert r["loss_last"] < r["loss_first"], r
        assert r["sync_hlo_has_all_reduce"] is True
    fast = res[0] if res[0]["rank"] == 0 else res[1]
    assert fast["gate_waits"] > 0, fast
    assert res[0]["param_fingerprint"] == res[1]["param_fingerprint"]
