"""CI gate contracts: the collect-only gate catches import-time
breakage, and the bench-regression comparator fails on >10% rows/sec
drops or silently-dropped sweep points (never on new points or wire-byte
movement)."""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "ci"))

from bench_regression import (cache_tripwires, compare, main,  # noqa: E402
                              throughput_points)


def _art(points):
    """Artifact with one sweep dict of {name: rows_per_sec_per_process}."""
    return {"metric": "m", "value": 1.0,
            "sweep": {k: {"rows_per_sec_per_process": v,
                          "wire_bytes_per_row_moved": 26.7}
                      for k, v in points.items()}}


def test_throughput_points_flattens_by_path():
    pts = throughput_points(_art({"a": 100.0, "b": 200.0}))
    assert pts == {"sweep/a": 100.0, "sweep/b": 200.0}


def test_within_tolerance_passes():
    prior, new = _art({"a": 100.0}), _art({"a": 91.0})
    assert compare(prior, new, 0.10) == []


def test_regression_beyond_tolerance_fails():
    prior, new = _art({"a": 100.0}), _art({"a": 89.0})
    problems = compare(prior, new, 0.10)
    assert len(problems) == 1 and "REGRESSED" in problems[0]
    assert "sweep/a" in problems[0]


def test_dropped_sweep_point_fails_new_point_passes():
    prior = _art({"a": 100.0})
    new = _art({"b": 50.0})  # 'a' vanished, 'b' is new
    problems = compare(prior, new, 0.10)
    assert len(problems) == 1 and "MISSING" in problems[0]
    # a brand-new point has no prior floor — never a failure by itself
    assert all("sweep/b" not in p for p in problems)


def test_zero_prior_point_cannot_define_a_floor():
    assert compare(_art({"a": 0.0}), _art({"a": 0.0}), 0.10) == []


def test_wire_bytes_are_not_gated():
    prior, new = _art({"a": 100.0}), _art({"a": 100.0})
    new["sweep"]["a"]["wire_bytes_per_row_moved"] = 999.0
    assert compare(prior, new, 0.10) == []


def _cache_art(hit_rates: dict) -> dict:
    """Artifact with a cache_comparison_3proc zipf grid:
    {s-name: on-arm hit rate}."""
    return {"cache_comparison_3proc": {"zipf": {
        s: {"on": {"rows_per_sec_per_process": 1.0,
                   "cache_hit_rate": hr},
            "off": {"rows_per_sec_per_process": 1.0}}
        for s, hr in hit_rates.items()}}}


def test_cache_tripwire_fails_on_zero_zipf_hit_rate_with_slack():
    """The 'cache silently disabled' tripwire: zipf + s >= 1 + cache on
    must show hit-rate > 0 — zero (or missing) means the lever fell off
    even if rows/sec still looks plausible."""
    problems = cache_tripwires(_cache_art({"s1": 0.0, "s2": 0.31}))
    assert len(problems) == 1 and "zipf/s1" in problems[0]
    assert cache_tripwires(_cache_art({"s1": None, "s2": 0.31}))
    assert cache_tripwires(_cache_art({"s2": {}}))  # field absent


def test_cache_tripwire_exempts_bsp_and_healthy_arms():
    # s=0 (BSP) CANNOT hit across clocks — zero is the correct reading
    assert cache_tripwires(_cache_art({"s0": 0.0, "s1": 0.2,
                                       "s2": 0.4})) == []
    # an artifact without the sweep (other benches) is not this gate's
    # business; a DROPPED sweep is the generic MISSING check's
    assert cache_tripwires({"metric": "m"}) == []


def test_cache_sweep_points_count_toward_missing_detection():
    """Every cache_comparison arm carries rows_per_sec_per_process, so
    the generic dropped-point gate covers the sweep with no extra
    wiring — dropping the zipf/s2 'on' arm fails."""
    prior = _cache_art({"s1": 0.2, "s2": 0.4})
    new = _cache_art({"s1": 0.2})
    problems = compare(prior, new, 0.10)
    assert any("MISSING" in p and "s2" in p for p in problems)


def test_main_end_to_end_exit_codes(tmp_path):
    p, n = tmp_path / "prior.json", tmp_path / "new.json"
    p.write_text(json.dumps(_art({"a": 100.0})))
    n.write_text(json.dumps(_art({"a": 95.0})))
    assert main([str(p), str(n)]) == 0
    n.write_text(json.dumps(_art({"a": 50.0})))
    assert main([str(p), str(n)]) == 1


@pytest.mark.slow
def test_collect_gate_collects_clean():
    """The real gate against the real tree: `pytest --collect-only` must
    exit 0 — the two seed collection errors (missing hypothesis) are the
    regression this pins."""
    proc = subprocess.run(
        ["bash", str(REPO / "ci" / "collect_gate.sh")],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    summary = proc.stdout.strip().splitlines()[-1]
    assert "collected" in summary and "error" not in summary, summary
