"""Benchmark harness — emits ONE JSON line for the driver.

Primary metric (BASELINE.json:2): **samples/sec/chip, LR + MLP on
Criteo-shaped data**. The reference publishes no numbers (BASELINE.json:14
``"published": {}``); the quantitative anchor is the north-star target of
>= 1M samples/sec aggregate on a TPU v4-32 (16 chips) for LR + 3-layer MLP
on Criteo with SSP staleness <= 4 (BASELINE.json:3-4) → 62,500
samples/sec/chip; ``vs_baseline`` = measured / target. Off-TPU runs report
``vs_baseline: null`` — a CPU fallback must never masquerade as a TPU
number (VERDICT r1 weak #7).

Round-2 credibility upgrades (VERDICT r1 "Next round" #2):

- **Chained-scan timing**: K steps are folded into ONE dispatch via
  ``lax.scan`` over the pure fused-step transition with donated state, and
  the reported rate is the median of R such calls — the tunneled chip in
  this sandbox has a ~0.1 s dispatch floor and ±40% call-to-call noise
  that per-step host timing cannot see through.
- **FLOP accounting**: every suite reports analytic matmul FLOPs/step,
  achieved TFLOP/s, and MFU against the chip's bf16 peak (by device_kind)
  so the headline survives arithmetic (a rate implying > peak is a bug,
  not a result).
- **Suites where MFU is meaningful**: ``lm`` (decoder LM with the flash-
  attention kernel, bf16 compute) and ``wd`` (Wide&Deep with a 2^22-slot
  embedding table — the memory-bound end) alongside the primary
  ``lrmlp``.
- **e2e**: streams a Criteo-format TSV from disk through the (native if
  available) parser and a prefetch thread into the fused step —
  samples/sec INCLUDING input IO, which the microbench deliberately
  excludes.

Usage: python bench.py [--cpu] [--suite all|lrmlp|lm|wd|mf|w2v|e2e|ps]

Round 3 adds ``mf`` and ``w2v`` so every BASELINE.json workload config
(1-2 lrmlp, 3 mf, 4 wd, 5 w2v) has a measured per-config rate.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

# bf16 peak matmul TFLOP/s per chip, by jax device_kind (public specs).
# MFU is reported against bf16 peak even for f32 suites — a deliberate
# lower bound, labeled as such.
_BF16_PEAK = {
    "TPU v2": 46e12, "TPU v3": 123e12, "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v5": 459e12, "TPU v5p": 459e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def _peak_for(device) -> float | None:
    kind = getattr(device, "device_kind", "")
    if kind in _BF16_PEAK:
        return _BF16_PEAK[kind]
    for k, v in _BF16_PEAK.items():  # e.g. "TPU v5 lite chip"
        if kind.startswith(k):
            return v
    return None


def _tpu_responsive(timeout_s: float = 180.0) -> tuple[bool, bool]:
    """One probe of the real chip in a SUBPROCESS: a hung axon tunnel
    blocks ops forever in-process and cannot be cancelled, so the probe
    must be killable. 180s covers a slow first compile (~20-40s
    normally).

    Returns ``(ok, permanent)``: ``permanent=True`` when the failure is
    deterministic absence (no TPU platform registered with jax on this
    host at all), which the retry window must not burn ~600s on. A
    timeout, an init failure, or a crash is the flapping-tunnel shape
    and stays retryable.

    The CLASSIFICATION happens inside the probe subprocess itself, which
    emits one of three sentinels (ADVICE r4 low: the old parent-side
    heuristic parsed jax's stderr for exact message substrings plus a
    wall-clock bound — a jax version changing either message would
    either stall TPU-less hosts the full window or write a flapping
    tunnel off as permanent):

    - ``MINIPS_PROBE_OK``          — chip answered a real matmul
    - ``MINIPS_PROBE_NO_TPU``      — ``jax.devices('tpu')`` says no such
      platform exists here (deterministic absence → permanent)
    - ``MINIPS_PROBE_INIT_FAILED`` — a TPU platform exists but failed to
      initialize (flap shape → retryable)"""
    import subprocess

    code = (
        "import sys\n"
        "import jax, jax.numpy as jnp\n"
        "def ok(ds):\n"
        "    x = jax.device_put(jnp.ones((8, 8)), ds[0])\n"
        "    jax.block_until_ready(x @ x)\n"
        "    print('MINIPS_PROBE_OK')\n"
        "try:\n"
        "    ds = jax.devices('tpu')\n"
        "except RuntimeError as e:\n"
        "    # an alive accelerator registered under a non-'tpu' platform\n"
        "    # name must still count as OK: this sandbox's plugin\n"
        "    # registers platform name 'axon' (jax logs \\\"Platform\n"
        "    # 'axon' is experimental\\\"). But ONLY tpu-ish platforms —\n"
        "    # a CUDA/METAL host must not masquerade as a chip in the\n"
        "    # captured artifact\n"
        "    if jax.default_backend() != 'cpu':\n"
        "        tds = [d for d in jax.devices()\n"
        "               if 'tpu' in d.platform.lower()\n"
        "               or 'axon' in d.platform.lower()]\n"
        "        if tds:\n"
        "            ok(tds)\n"
        "            sys.exit(0)\n"
        "    # jax raises RuntimeError both when no tpu platform exists\n"
        "    # and when one failed to init; only DETERMINISTIC absence\n"
        "    # is permanent. The distinction is made HERE, in the\n"
        "    # subprocess, against the exception for the 'tpu' request\n"
        "    # we made — not by the parent parsing whatever jax logged\n"
        "    # while falling back. jax registers a 'tpu' factory\n"
        "    # unconditionally, so on a TPU-less host the shape is\n"
        "    # 'failed to initialize: <libtpu IMPORT error>'. Only the\n"
        "    # module-import family counts as absent — a device-file or\n"
        "    # tunnel error ('could not open /dev/accel0: no such\n"
        "    # file', gRPC 'not found') is a restartable-runtime flap\n"
        "    # and must stay retryable.\n"
        "    msg = str(e).lower()\n"
        "    absent = ('unknown backend' in msg or 'no platforms' in msg\n"
        "              or 'no module named' in msg\n"
        "              or ('libtpu' in msg and any(s in msg for s in (\n"
        "                  'cannot open shared object', 'not installed',\n"
        "                  'no such file'))))\n"
        "    print('MINIPS_PROBE_NO_TPU' if absent\n"
        "          else 'MINIPS_PROBE_INIT_FAILED')\n"
        "    print(repr(e), file=sys.stderr)\n"
        "    sys.exit(3)\n"
        "ok(ds)\n")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return False, False
    if proc.returncode == 0 and "MINIPS_PROBE_OK" in proc.stdout:
        return True, False
    return False, "MINIPS_PROBE_NO_TPU" in proc.stdout


def _default_probe_window() -> float:
    import os

    try:
        return float(os.environ.get("MINIPS_PROBE_WINDOW", "600"))
    except ValueError:
        return 600.0


def _tpu_available(window_s: float | None = None) -> bool:
    """Probe with a bounded RETRY WINDOW. The round-3 record was forfeited
    by a single-shot probe meeting a flapping tunnel at capture time
    (VERDICT r3 missing #1): the tunnel demonstrably dies and returns
    within a round, so one 180s attempt at the driver's capture moment is
    the difference between a round with a TPU record and a round without
    one.

    Policy: attempt 1 gets the full 180s budget regardless of window
    (covers a cold first compile; ``window_s=0`` therefore restores
    exactly the old single-shot behavior); while the window has time
    left, re-probe after a 30s pause with a budget clamped to the
    smaller of 120s and the time remaining — the window is a bound, not
    a hint. Default window: ``MINIPS_PROBE_WINDOW`` env or 600s
    (resolved in ``main``; ``window_s=None`` here re-resolves for
    direct callers). Every attempt is logged to stderr so the captured
    artifact shows the probe history. The off-TPU refusal stays sticky:
    once a run labels itself CPU it never flips back (that invariant
    lives at the call sites)."""
    if window_s is None:
        window_s = _default_probe_window()
    deadline = time.time() + window_s
    attempt = 0
    while True:
        attempt += 1
        t0 = time.time()
        budget = (180.0 if attempt == 1
                  else min(120.0, max(deadline - time.time(), 5.0)))
        ok, permanent = _tpu_responsive(budget)
        took = time.time() - t0
        if ok:
            if attempt > 1:
                print(f"bench: TPU probe attempt {attempt} succeeded "
                      f"after earlier failures ({took:.0f}s)",
                      file=sys.stderr)
            return True
        if permanent:
            # the probe subprocess classified the failure as
            # deterministic absence (MINIPS_PROBE_NO_TPU: no tpu-ish
            # platform, libtpu not installed): retrying is futile — fall
            # back now instead of stalling a TPU-less machine ~window
            # seconds at startup
            print(f"bench: no TPU runtime on this host (probe attempt "
                  f"{attempt} reported deterministic absence, "
                  f"{took:.0f}s); not retrying", file=sys.stderr)
            return False
        remaining = deadline - time.time()
        if remaining <= 0:
            print(f"bench: TPU probe attempt {attempt} failed "
                  f"({took:.0f}s); retry window exhausted",
                  file=sys.stderr)
            return False
        pause = min(30.0, remaining)
        print(f"bench: TPU probe attempt {attempt} failed ({took:.0f}s); "
              f"retrying in {pause:.0f}s ({remaining:.0f}s left in "
              "window)", file=sys.stderr)
        time.sleep(pause)


def _mlp_flops_per_sample(sizes) -> float:
    """Matmul-only analytic cost: fwd = 2·MACs, bwd ≈ 2× fwd → 3× fwd."""
    fwd = sum(2 * a * b for a, b in zip(sizes[:-1], sizes[1:]))
    return 3.0 * fwd


_PROFILE_DIR = None  # set by --profile: capture one steady-state rep


def _chain_timed(jitted_chain, state, reps):
    """Median seconds per chained call. The chain is compiled once; each
    timed call is one dispatch running K steps on device; block on the
    returned loss so the timer covers the device work. With --profile one
    EXTRA steady-state rep runs under jax.profiler before the timed loop
    — captured but never timed, so profiler overhead can't leak into the
    reported numbers at any --reps."""
    import jax

    state, loss = jitted_chain(state)          # compile + warmup
    jax.block_until_ready(loss)
    if _PROFILE_DIR:
        from minips_tpu.utils.profiling import profile_trace
        with profile_trace(_PROFILE_DIR):
            state, loss = jitted_chain(state)  # captured, untimed
            jax.block_until_ready(loss)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, loss = jitted_chain(state)
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    return state, statistics.median(times)


def _suite_result(samples, dt, n_chips, flops_per_step, peak):
    sps_chip = samples / dt / n_chips
    tflops = flops_per_step / dt / 1e12 / n_chips  # per chip
    out = {"samples_per_sec_per_chip": round(sps_chip, 1),
           # 9 decimals: tiny CPU-validation runs live in the micro-TFLOP
           # range (the mf suite's analytic cost is ~200k FLOPs/call at
           # test shapes) and must not round to a test-failing hard zero
           "tflops_per_chip": round(tflops, 9),
           "mfu_vs_bf16_peak": (round(tflops * 1e12 / peak, 4)
                                if peak else None)}
    if peak and tflops * 1e12 > peak:
        out["warning"] = ("achieved TFLOP/s exceeds chip peak — timing or "
                          "FLOP accounting is broken; do not trust")
    return out


def _batch_rotation(batches, K):
    """Stack >= 2 DISTINCT batches and return ``(stacked, idx)`` where
    ``idx`` is the scan's xs (step -> batch index). The body dynamically
    gathers its step's batch from ``stacked``, so per-batch work (key
    hashing, dedup, sort) varies across scan iterations and XLA cannot
    hoist it out of the timed region — the loop-invariant-batch hazard of
    VERDICT r2 weak #5. Real training pays that cost on every fresh
    batch; now the microbenches do too."""
    import jax
    import jax.numpy as jnp

    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *batches)
    return stacked, jnp.arange(K) % len(batches)


def _pick(stacked, i):
    import jax

    return jax.tree.map(lambda l: l[i], stacked)


def _ps_chain_timed(ps, batches, args, k_div=2):
    """Chained-scan timing for one PSTrainStep: rotate the given distinct
    sharded batches through K = max(chain//k_div, 2) steps in a single
    donated-state ``lax.scan`` dispatch (shared by the wd/mf/w2v suites —
    the timing contract lives in exactly one place). Returns
    ``(K, dt, final_state)``; final_state is live (the initial state's
    buffers were donated into the chain)."""
    import functools

    import jax

    K = max(args.chain // k_div, 2)
    stacked, idx = _batch_rotation(batches, K)
    pure = ps.step_fn_pure

    @functools.partial(jax.jit, donate_argnums=(0,))
    def chained(state):
        def body(s, i):
            s2, loss = pure(s, _pick(stacked, i))
            return s2, loss
        s, losses = jax.lax.scan(body, state, idx)
        return s, losses[-1]

    state, dt = _chain_timed(chained, ps._collect_state(), args.reps)
    return K, dt, state


# --------------------------------------------------------------- suites
def bench_lrmlp(args, n_chips, peak):
    """The primary metric: every sample through BOTH fused steps (sparse
    LR and the 3-layer MLP over dense+embeddings), f32 masters."""
    import functools

    import jax
    import jax.numpy as jnp

    from minips_tpu.data import synthetic
    from minips_tpu.models import lr as lr_model
    from minips_tpu.models import mlp as mlp_model
    from minips_tpu.models import wide_deep as wd_model
    from minips_tpu.parallel.mesh import make_mesh
    from minips_tpu.tables.dense import DenseTable
    from minips_tpu.tables.sparse import SparseTable
    from minips_tpu.train.ps_step import PSTrainStep

    mesh = make_mesh()
    B = args.batch
    data = synthetic.criteo_like(B, seed=0)
    data2 = synthetic.criteo_like(B, seed=1)

    wide_t = SparseTable(1 << 18, 1, mesh, name="wide", updater="adagrad",
                         lr=0.05, init_scale=0.0, salt=1)
    lin_t = DenseTable(lr_model.init(13), mesh, name="lin",
                       updater="adagrad", lr=0.05)

    def lr_loss(dp, rows, batch):
        logits = (jnp.sum(rows["wide"][..., 0], axis=-1)
                  + lr_model.logits_dense(dp, batch["dense"]))
        return lr_model.bce_with_logits(logits, batch["y"])

    lr_step = PSTrainStep(lr_loss, dense=lin_t, sparse={"wide": wide_t},
                          key_fns={"wide": lambda b: b["cat"]})

    emb_t = SparseTable(1 << 18, 8, mesh, name="emb", updater="adagrad",
                        lr=0.05, init_scale=0.01, salt=2)
    deep_t = DenseTable(
        wd_model.init_deep(jax.random.PRNGKey(0), 26, 8, 13,
                           hidden=(256, 128)),
        mesh, name="deep", updater="adam", lr=1e-3)

    def mlp_loss(dp, rows, batch):
        bsz = rows["emb"].shape[0]
        x = jnp.concatenate([batch["dense"], rows["emb"].reshape(bsz, -1)],
                            axis=-1)
        logits = mlp_model.apply(dp, x)[:, 0]
        return lr_model.bce_with_logits(logits, batch["y"])

    mlp_step = PSTrainStep(mlp_loss, dense=deep_t, sparse={"emb": emb_t},
                           key_fns={"emb": lambda b: b["cat"]})

    # one chained program runs BOTH models' pure transitions K times,
    # rotating 2 distinct batches so per-batch hash/dedup stays timed
    lr_pure, mlp_pure = lr_step.step_fn_pure, mlp_step.step_fn_pure
    K = args.chain
    stacked, idx = _batch_rotation(
        [lr_step.shard_batch(data), lr_step.shard_batch(data2)], K)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def chained(state):
        def body(s, i):
            b = _pick(stacked, i)
            s1, l1 = lr_pure(s[0], b)
            s2, l2 = mlp_pure(s[1], b)
            return (s1, s2), (l1, l2)
        s, losses = jax.lax.scan(body, state, idx)
        return s, jax.tree.map(lambda x: x[-1], losses)

    state = (lr_step._collect_state(), mlp_step._collect_state())
    state, dt = _chain_timed(chained, state, args.reps)

    flops_step = B * K * (
        _mlp_flops_per_sample((13 + 26 * 8, 256, 128, 1))   # deep tower
        + _mlp_flops_per_sample((13, 1)))                   # LR linear
    return _suite_result(B * K, dt, n_chips, flops_step, peak)


def bench_lm(args, n_chips, peak):
    """Decoder LM with the flash-attention kernel, bf16 compute — the
    suite where MFU is meaningful (matmul-dominated)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from minips_tpu.models import transformer as tfm
    from minips_tpu.parallel.mesh import make_mesh
    from minips_tpu.tables.dense import DenseTable

    mesh = make_mesh()
    B, T = args.lm_batch, args.lm_seq
    D, depth, heads = args.lm_dim, args.lm_depth, args.lm_dim // 64
    vocab = 1 << 14
    params = tfm.init(jax.random.PRNGKey(0), vocab=vocab, dim=D,
                      heads=heads, depth=depth, max_len=T,
                      kv_heads=args.lm_kv_heads, rope=args.lm_rope)
    # optimizer-state memory lever (tables/updaters.py): f32 adam state
    # is what HBM-bounds the frontier (BASELINE.md); bf16 moments halve
    # it, int8 blockwise quarters it — buying batch/seq headroom
    updater = {"f32": "adam", "bf16": "adam_bf16",
               "int8": "adam8"}[args.lm_opt_state]
    table = DenseTable(params, mesh, name="lm", updater=updater, lr=1e-3)
    attn = "flash" if jax.default_backend() == "tpu" else "reference"
    remat = False
    if args.lm_remat:
        remat = (True if args.lm_remat_mode == "full"
                 else args.lm_remat_mode)
    step = table.make_step(
        functools.partial(tfm.grad_fn, heads=heads, attn_impl=attn,
                          remat=remat, head_chunk=args.lm_head_chunk),
        jit=False, compute_dtype=jnp.bfloat16)

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from minips_tpu.parallel.mesh import DATA_AXIS

    rng = np.random.default_rng(0)
    sh = NamedSharding(mesh, P(DATA_AXIS))
    K = max(args.chain // 4, 2)
    stacked, idx = _batch_rotation(
        [{"tokens": jax.device_put(
            jnp.asarray(rng.integers(0, vocab, size=(B, T + 1))), sh)}
         for _ in range(2)], K)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def chained(state):
        def body(s, i):
            p, o, loss = step(s[0], s[1], _pick(stacked, i))
            return (p, o), loss
        s, losses = jax.lax.scan(body, state, idx)
        return s, losses[-1]

    state, dt = _chain_timed(chained, (table.params, table.opt_state),
                             args.reps)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    tokens = B * T
    m_mat = 6.0 * n_params * tokens                 # matmul 6PT
    m_attn = 12.0 * B * T * T * D * depth * 0.5     # causal attn fwd+bwd
    flops_step = K * (m_mat + m_attn)
    out = _suite_result(K * tokens, dt, n_chips, flops_step, peak)
    out["config"] = {"dim": D, "depth": depth, "batch": B, "seq": T,
                     "remat": (args.lm_remat_mode if args.lm_remat
                               else False),
                     "head_chunk": args.lm_head_chunk,
                     "opt_state": args.lm_opt_state}
    opt_leaves = [x for x in jax.tree.leaves(state[1])
                  if hasattr(x, "dtype")]
    out["opt_state_bytes"] = int(sum(
        x.size * x.dtype.itemsize for x in opt_leaves))
    if args.lm_kv_heads:
        out["kv_heads"] = args.lm_kv_heads
    if args.lm_rope:
        out["rope"] = True
    # HONEST dual accounting: mfu_vs_bf16_peak above is MODEL-FLOPs MFU
    # (the number people compare across systems); remat/chunked-CE
    # recompute is real chip work that the model number hides, so also
    # report the executed estimate and the hardware MFU it implies —
    # without it, "remat costs nothing" would be silently claimable.
    extra = 0.0
    if remat is True:
        extra += (m_mat + m_attn) / 3.0      # whole forward again
    elif remat == "attn":
        extra += m_mat / 3.0                 # forward minus attention
    elif remat == "hybrid":
        extra += m_mat / 9.0            # qkv + attn out-proj: 8/24 of fwd
    elif remat == "hybrid_qkv":
        extra += m_mat / 36.0           # attn out-proj only: 2/24 of fwd
    # "dots" recomputes only elementwise: ~0 extra matmul FLOPs
    if args.lm_head_chunk:
        # backward re-runs the tied-head matmul once per chunk
        extra += 2.0 * vocab * D * tokens
    if extra > 0:
        hw = (flops_step + K * extra) / dt / 1e12 / n_chips
        out["tflops_hw_per_chip"] = round(hw, 6)
        out["mfu_hw_vs_bf16_peak"] = (round(hw * 1e12 / peak, 4)
                                      if peak else None)
        out["recompute_factor"] = round(1.0 + extra / (m_mat + m_attn), 4)
    return out


def bench_wd(args, n_chips, peak):
    """Wide&Deep with a 2^22-slot embedding table (BASELINE config 4's
    scale direction): the memory-bound end — gathers/scatter-adds over a
    268 MB table dominate, so MFU is expected to be tiny; the honest
    numbers are rows/sec and achieved TFLOP/s."""
    import jax
    import jax.numpy as jnp

    from minips_tpu.core.config import Config, TableConfig, TrainConfig
    from minips_tpu.data import synthetic
    from minips_tpu.apps.wide_deep_example import build

    cfg = Config(
        table=TableConfig(name="ctr", kind="sparse", consistency="bsp",
                          updater="adagrad", lr=0.05, dim=8,
                          num_slots=args.wd_slots),
        train=TrainConfig(batch_size=args.batch, num_iters=1),
    )
    ps, _tables = build(cfg, use_fm=True, compute_dtype=jnp.bfloat16)
    batches = [ps.shard_batch(synthetic.criteo_like(args.batch, seed=s))
               for s in (0, 1)]
    K, dt, state = _ps_chain_timed(ps, batches, args)
    flops_step = args.batch * K * _mlp_flops_per_sample(
        (13 + 26 * 8, 256, 128, 1))
    out = _suite_result(K * args.batch, dt, n_chips, flops_step, peak)
    out["emb_slots"] = args.wd_slots
    if n_chips > 1:
        # collective traffic of ONE fused step: must be batch-sized, never
        # table-sized (VERDICT task 6; tests/test_sharded_traffic.py pins
        # the same invariant on the raw SparseTable ops). `state` is the
        # post-timing live state from the helper.
        from minips_tpu.utils.comm_analysis import traffic_report
        rep = traffic_report(
            jax.jit(ps.step_fn_pure).lower(state, batches[0]).compile())
        out["step_collective_bytes"] = rep["total_bytes"]
    return out


def bench_mf(args, n_chips, peak):
    """Matrix factorization (BASELINE config 3's workload shape —
    MovieLens-scale id spaces): per-key pull/push of user and item factor
    rows through two SparseTables, the pure embedding-bound end of the
    suite family. The honest numbers are ratings/sec and achieved
    TFLOP/s; MFU is expected to be tiny (dot products, no matmul)."""
    import jax.numpy as jnp
    import numpy as np

    from minips_tpu.models import mf as mf_model
    from minips_tpu.parallel.mesh import make_mesh
    from minips_tpu.tables.sparse import SparseTable
    from minips_tpu.train.ps_step import PSTrainStep

    mesh = make_mesh()
    B, dim = args.batch, args.mf_dim
    users, items = args.mf_users, args.mf_items
    # sgd, matching the app's default updater — under sgd grad_scale=B
    # below genuinely restores per-sample server-add magnitude (adagrad
    # rows would be invariant to a constant scale)
    user_t = SparseTable(users, dim, mesh, name="user",
                         updater="sgd", lr=0.05, init_scale=0.1,
                         seed=1)
    item_t = SparseTable(items, dim, mesh, name="item",
                         updater="sgd", lr=0.05, init_scale=0.1,
                         seed=2)

    def loss_fn(dense_params, rows, batch):
        return mf_model.loss(rows["user"], rows["item"], batch["rating"],
                             mu=3.5, reg=0.02)

    # grad_scale=B: per-sample server-add magnitude (see mf_example)
    ps = PSTrainStep(loss_fn, sparse={"user": user_t, "item": item_t},
                     key_fns={"user": lambda b: b["user"],
                              "item": lambda b: b["item"]},
                     grad_scale=B)

    def batch(seed):
        r = np.random.default_rng(seed)
        return ps.shard_batch({
            "user": jnp.asarray(r.integers(0, users, size=B)),
            "item": jnp.asarray(r.integers(0, items, size=B)),
            "rating": jnp.asarray(
                r.integers(1, 6, size=B).astype(np.float32))})

    K, dt, _ = _ps_chain_timed(ps, [batch(0), batch(1)], args)
    # fwd = the u·i dot (2·dim FLOPs/sample); bwd ≈ 2x fwd
    flops_step = K * B * 3.0 * 2.0 * dim
    out = _suite_result(K * B, dt, n_chips, flops_step, peak)
    out["factor_dim"] = dim
    out["id_space"] = [users, items]
    return out


def bench_w2v(args, n_chips, peak):
    """Word2vec SGNS (BASELINE config 5's workload shape — enwiki-scale
    vocab): center/context/negative rows through two SparseTables with
    host-side alias-table negative sampling baked into the rotated
    batches, per-pair update magnitude via grad_scale. pairs/sec is the
    headline; like mf this is gather/scatter-bound."""
    import jax.numpy as jnp
    import numpy as np

    from minips_tpu.models import word2vec as w2v
    from minips_tpu.parallel.mesh import make_mesh
    from minips_tpu.tables.sparse import SparseTable
    from minips_tpu.train.ps_step import PSTrainStep

    mesh = make_mesh()
    B, dim, vocab, neg = (args.batch, args.w2v_dim, args.w2v_vocab,
                          args.w2v_neg)
    # sgd per the app's default — see the bench_mf updater note
    in_t = SparseTable(vocab, dim, mesh, name="in", updater="sgd",
                       lr=0.05, init_scale=0.01, seed=1)
    out_t = SparseTable(vocab, dim, mesh, name="out", updater="sgd",
                        lr=0.05, init_scale=0.0, seed=2)

    def loss_fn(dense_params, rows, batch):
        return w2v.sgns_loss(rows["in"], rows["out"][:, 0],
                             rows["out"][:, 1:])

    ps = PSTrainStep(
        loss_fn, sparse={"in": in_t, "out": out_t},
        key_fns={"in": lambda b: b["center"],
                 "out": lambda b: jnp.concatenate(
                     [b["pos"][:, None], b["neg"]], axis=1)},
        grad_scale=B)

    # zipf-shaped unigram counts -> the classic 0.75-power alias table;
    # negatives are drawn per rotated batch on the host, exactly like
    # the app's batch generator (word2vec_example._batch_gen)
    counts = 1.0 / np.arange(1, vocab + 1)
    sampler = w2v.UnigramSampler(np.asarray(counts), power=0.75, seed=0)

    def batch(seed):
        r = np.random.default_rng(seed)
        return ps.shard_batch({
            "center": jnp.asarray(r.integers(0, vocab, size=B)),
            "pos": jnp.asarray(r.integers(0, vocab, size=B)),
            "neg": jnp.asarray(sampler.sample((B, neg)))})

    K, dt, _ = _ps_chain_timed(ps, [batch(0), batch(1)], args)
    # fwd = (1 pos + neg) center·context dots of 2·dim each; bwd ≈ 2x
    flops_step = K * B * 3.0 * 2.0 * dim * (1 + neg)
    out = _suite_result(K * B, dt, n_chips, flops_step, peak)
    out["vocab"] = vocab
    out["dim"] = dim
    out["negatives"] = neg
    return out


def bench_e2e(args, n_chips):
    """End-to-end: Criteo-format TSV on disk → (native) parser → prefetch
    thread → fused LR+MLP steps. samples/sec INCLUDING IO — the number the
    microbench suites deliberately exclude (BASELINE.json:2 names the
    workload 'on Criteo', not 'on resident arrays')."""
    import os
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from minips_tpu.data import synthetic
    from minips_tpu.data.criteo import (log_transform,
                                        stream_criteo_batches, write_criteo)
    from minips_tpu.data.loader import prefetch_to_device
    from minips_tpu.models import lr as lr_model
    from minips_tpu.models import mlp as mlp_model
    from minips_tpu.models import wide_deep as wd_model
    from minips_tpu.parallel.mesh import make_mesh
    from minips_tpu.tables.dense import DenseTable
    from minips_tpu.tables.sparse import SparseTable
    from minips_tpu.train.ps_step import PSTrainStep

    rows = args.e2e_rows
    d = synthetic.criteo_like(rows, seed=3)
    fd, path = tempfile.mkstemp(suffix=".tsv")
    os.close(fd)
    try:
        dense_raw = np.maximum(
            (d["dense"] * 10).astype(np.int64), 0)
        write_criteo(path, d["y"], dense_raw, d["cat"])

        mesh = make_mesh()
        wide_t = SparseTable(1 << 18, 1, mesh, name="wide",
                             updater="adagrad", lr=0.05, init_scale=0.0,
                             salt=1)
        lin_t = DenseTable(lr_model.init(13), mesh, name="lin",
                           updater="adagrad", lr=0.05)
        emb_t = SparseTable(1 << 18, 8, mesh, name="emb",
                            updater="adagrad", lr=0.05, salt=2)
        deep_t = DenseTable(
            wd_model.init_deep(jax.random.PRNGKey(0), 26, 8, 13,
                               hidden=(256, 128)),
            mesh, name="deep", updater="adam", lr=1e-3)

        def lr_loss(dp, rws, b):
            logits = (jnp.sum(rws["wide"][..., 0], axis=-1)
                      + lr_model.logits_dense(dp, b["dense"]))
            return lr_model.bce_with_logits(logits, b["y"])

        def mlp_loss(dp, rws, b):
            bsz = rws["emb"].shape[0]
            x = jnp.concatenate([b["dense"],
                                 rws["emb"].reshape(bsz, -1)], axis=-1)
            return lr_model.bce_with_logits(
                mlp_model.apply(dp, x)[:, 0], b["y"])

        lr_step = PSTrainStep(lr_loss, dense=lin_t,
                              sparse={"wide": wide_t},
                              key_fns={"wide": lambda b: b["cat"]})
        mlp_step = PSTrainStep(mlp_loss, dense=deep_t,
                               sparse={"emb": emb_t},
                               key_fns={"emb": lambda b: b["cat"]})

        B = args.e2e_batch
        # compile warmup OUTSIDE the timed region (compile is once-ever,
        # the steady-state pipeline is the thing being measured)
        warm = synthetic.criteo_like(B, seed=4)
        wb = lr_step.shard_batch(warm)
        lr_step(wb)
        loss = mlp_step(wb)
        jax.block_until_ready(loss)

        t0 = time.perf_counter()
        try:  # flag which parser actually RAN inside the stream
            from minips_tpu.data.native import native_mem_available
            native = native_mem_available()
        except ImportError:
            native = False

        def xform(d):  # runs on the producer thread, off the train thread
            return {"dense": log_transform(d["dense"], d["dense_mask"]),
                    "cat": d["cat"], "y": d["y"]}

        # streaming ingestion: blocks parse on a producer thread WHILE
        # prior batches train — parse overlaps compute, working set is one
        # block, never the file (the Criteo-1TB posture, SURVEY.md §7.4.4)
        stream_stats: dict = {}
        batches = stream_criteo_batches(path, B, chunk_bytes=4 << 20,
                                        transform=xform, stats=stream_stats)
        n_done = 0
        loss = None
        for batch in prefetch_to_device(
                batches, lr_step.shard_batch, depth=2):
            lr_step(batch)
            loss = mlp_step(batch)
            n_done += B
            if n_done >= rows:
                break
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
    finally:
        os.unlink(path)
    return {"samples_per_sec_per_chip": round(n_done / dt / n_chips, 1),
            "rows": n_done, "native_parser": native,
            # no-silent-caps: rows short of a final batch (0 when the break
            # above fires before EOF — the stream was abandoned, not short)
            "dropped_rows": stream_stats.get("dropped_rows", 0),
            "includes_io": True}


def _emit(suites, on_tpu, device_note, device_kind, peak_tflops,
          failed=()) -> None:
    """The ONE place the headline metric line is assembled (single-suite
    and --suite all runs must agree on labels, the north-star constant,
    and the off-TPU vs_baseline refusal)."""
    unit = "samples/sec/chip"
    if "lrmlp" in suites:
        sps = suites["lrmlp"]["samples_per_sec_per_chip"]
        # north-star: 1M samples/sec aggregate on v4-32 = 16 chips
        metric = ("samples/sec/chip (LR+MLP on Criteo-shaped, fused SPMD, "
                  "chained-scan median)")
        vs = round(sps / (1_000_000 / 16), 4) if on_tpu else None
    else:
        only = next(iter(suites))
        sps = suites[only].get("samples_per_sec_per_chip")
        metric = f"samples/sec/chip ({only} suite — NOT the primary " \
                 "LR+MLP metric)"
        if sps is None:  # ps suites: control-plane rates, not chip rates
            sps = suites[only]["rows_per_sec_per_process"]
            unit = "rows/sec/process"
            metric = suites[only].get(
                "metric_note",
                f"rows/sec/process ({only} suite, CPU loopback "
                "control plane — NOT the primary LR+MLP metric)")
        vs = None
    out = {
        "metric": metric,
        "value": sps,
        "unit": unit,
        "vs_baseline": vs,
        "device": device_note,
        "device_kind": device_kind,
        "bf16_peak_tflops": peak_tflops,
        "suites": suites,
    }
    if failed:
        out["failed_suites"] = sorted(failed)
    print(json.dumps(out))


def bench_ps(args) -> dict:
    """Sharded multi-process PS throughput (train/sharded_ps.py) over
    loopback — rows/sec and wire-bytes/sec of the pull→push cycle with
    model math stripped out (apps/sharded_ps_bench.py). This measures the
    CONTROL-PLANE data path (routing + serialization + bus + server
    updater) on host CPUs; it is deliberately NOT a chip rate and never
    feeds vs_baseline. bench_sharded_ps.py publishes the full curve
    (world sizes 1–4, zmq vs native mailbox, sparse vs dense range)."""
    from bench_sharded_ps import _run  # ONE spawn/aggregate protocol

    out = _run(3, "sparse", args.ps_iters, max(2, args.ps_iters // 6),
               "zmq")
    out.update(nprocs=3, bus="zmq", path="sparse",
               compute="cpu-loopback-control-plane")
    return out


def bench_ps_tpu(args, force_cpu: bool) -> dict:
    """The PS topology the north star actually describes (VERDICT r3
    next #5): sharded host PS + workers whose grad math is a REAL jitted
    step — rank 0 on the chip when it is alive, peers on CPU — so the
    row rate includes pull → device → MLP fwd+bwd → host → push
    overlapped with the wire. ``force_cpu`` (parent probe said the chip
    is dead) keeps rank 0 off the tunnel so a hung backend can't stall
    the suite; the labels say which ran."""
    from bench_sharded_ps import _run

    out = _run(3, "sparse", args.ps_iters, max(2, args.ps_iters // 6),
               "zmq", compute="jit", force_cpu=force_cpu,
               hidden=args.ps_hidden)
    out.update(nprocs=3, bus="zmq", path="sparse",
               metric_note="rows/sec/process (sharded PS + jitted worker"
                           " compute; rank 0 on "
                           + ("cpu-fallback" if force_cpu else "chip")
                           + ", peers cpu)")
    return out


def _run_all(args) -> int:
    """Parent for ``--suite all``: fork one child per suite (the parent
    never initializes JAX — see the call site), merge their JSON, publish
    one line. Device labeling is STICKY-DOWNGRADE: one child falling back
    to CPU taints the whole run (a later TPU child must not flip the
    label back and publish a CPU rate as a TPU vs_baseline)."""
    import os
    import subprocess

    suites = {}
    failed = []
    device_note = None
    device_kind = None
    peak_tflops = None
    if not args.cpu and not _tpu_available(args.probe_window):
        # probe ONCE here (with the full retry window), not once per
        # child: a dead tunnel would otherwise cost every chip suite its
        # own probe window before ITS fallback — 8x the wall clock for
        # the same answer
        print("bench: TPU unresponsive (parent probe window); all suites "
              "fall back to CPU", file=sys.stderr)
        args.cpu = True
        device_note = "cpu-fallback(tpu-unresponsive)"
    for s in ("lrmlp", "lm", "wd", "mf", "w2v", "e2e", "ps", "ps_tpu"):
        argv = [sys.executable, os.path.abspath(__file__),
                "--suite", s,
                "--batch", str(args.batch),
                "--chain", str(args.chain),
                "--reps", str(args.reps),
                "--lm-batch", str(args.lm_batch),
                "--lm-seq", str(args.lm_seq),
                "--lm-dim", str(args.lm_dim),
                "--lm-depth", str(args.lm_depth),
                ("--lm-remat" if args.lm_remat else "--no-lm-remat"),
                *(["--lm-kv-heads", str(args.lm_kv_heads)]
                  if args.lm_kv_heads else []),
                *(["--lm-rope"] if args.lm_rope else []),
                "--lm-remat-mode", args.lm_remat_mode,
                "--lm-head-chunk", str(args.lm_head_chunk),
                "--lm-opt-state", args.lm_opt_state,
                "--wd-slots", str(args.wd_slots),
                "--mf-users", str(args.mf_users),
                "--mf-items", str(args.mf_items),
                "--mf-dim", str(args.mf_dim),
                "--w2v-vocab", str(args.w2v_vocab),
                "--w2v-dim", str(args.w2v_dim),
                "--w2v-neg", str(args.w2v_neg),
                "--e2e-rows", str(args.e2e_rows),
                "--e2e-batch", str(args.e2e_batch),
                "--ps-iters", str(args.ps_iters),
                "--ps-hidden", str(args.ps_hidden),
                # parent already proved liveness with the full window;
                # a child's probe only guards against a MID-RUN flap, so
                # it gets a short window (one retry) — seven children
                # each burning a 600s window on a tunnel that died after
                # the parent probe would blow any capture budget. The
                # operator's window (flag or env, resolved in main) still
                # caps it: --probe-window 0 means single-shot for the
                # children too.
                "--probe-window", str(min(args.probe_window, 240.0))]
        if args.cpu:
            argv.append("--cpu")
        proc = subprocess.run(argv, capture_output=True, text=True)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        if proc.returncode != 0 or not lines:
            print(f"bench: suite {s} failed (rc={proc.returncode}):\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            failed.append(s)
            continue
        child = json.loads(lines[-1])
        suites.update(child.get("suites", {}))
        if s in ("ps", "ps_tpu"):
            # PS-topology suites label themselves (loopback control
            # plane / mixed rank0-chip) and must not taint the run's
            # device label (sticky-downgrade is about chip suites
            # silently falling back to CPU)
            continue
        dev = child.get("device", "?")
        if device_note is None:
            device_note = dev
        elif device_note == "tpu" and dev != "tpu":
            device_note = dev  # sticky downgrade; never flips back to tpu
        if device_kind is None:
            device_kind = child.get("device_kind")
            peak_tflops = child.get("bf16_peak_tflops")
    if not suites:
        print("bench: every suite failed", file=sys.stderr)
        return 1
    _emit(suites, device_note == "tpu", device_note, device_kind,
          peak_tflops, failed)
    # partial results must not read as a clean run to automation
    return 1 if failed else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (8 fake devices) for development")
    ap.add_argument("--suite", default="all",
                    choices=["all", "lrmlp", "lm", "wd", "mf", "w2v",
                             "e2e", "ps", "ps_tpu"])
    ap.add_argument("--ps-iters", type=int, default=40,
                    help="pull/push cycles per rank in the ps suite")
    ap.add_argument("--ps-hidden", type=int, default=256,
                    help="ps_tpu suite: hidden width of the jitted "
                         "worker MLP (the MXU work per cycle)")
    ap.add_argument("--probe-window", type=float, default=None,
                    help="TPU probe retry window in seconds (0 = single "
                         "attempt; default: MINIPS_PROBE_WINDOW env or "
                         "600). A flapping tunnel at capture time must "
                         "not forfeit the round's TPU record")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of one steady-state"
                         " rep into DIR and attach the top-op table to the"
                         " suite result (single-suite runs only; --suite "
                         "all forks children and ignores it)")
    # defaults = the measured sweet spots on the v5-lite here (2026-07-30
    # sweep: 16k->65k batch buys +13% lrmlp and +11% wd; lm saturates MFU
    # at micro-batch 64 and regresses at 128)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument("--chain", type=int, default=20,
                    help="steps folded into one dispatch (lax.scan)")
    ap.add_argument("--reps", type=int, default=5,
                    help="timed chained calls; median reported")
    # lm defaults = the measured 2026-07-31 frontier winner (43.5% model
    # MFU on the v5 lite: d=2048x8, B=16, remat=dots, chunked head 128 —
    # BASELINE.md / sweep_lm.sh); the r2 base config is reproducible with
    # --lm-dim 512 --lm-depth 4 --lm-batch 64 --no-lm-remat
    # --lm-head-chunk 0. CPU validation runs clamp the shapes anyway.
    ap.add_argument("--lm-batch", type=int, default=16)
    ap.add_argument("--lm-seq", type=int, default=1024)
    ap.add_argument("--lm-dim", type=int, default=2048)
    ap.add_argument("--lm-depth", type=int, default=8)
    ap.add_argument("--lm-kv-heads", type=int, default=None,
                    help="grouped-query attention KV heads (1 = MQA; "
                         "default = dim/64 q-heads, classic MHA) — "
                         "shrinks KV projection + activations")
    ap.add_argument("--lm-rope", action="store_true",
                    help="rotary position embeddings instead of the "
                         "learned table")
    ap.add_argument("--lm-remat", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="recompute block activations in backward "
                         "(fits larger --lm-dim/--lm-depth in HBM)")
    ap.add_argument("--lm-remat-mode", default="dots",
                    choices=["full", "attn", "dots", "hybrid",
                             "hybrid_qkv"],
                    help="with --lm-remat: full = recompute whole blocks; "
                         "attn = save attention outputs (backward never "
                         "re-runs attention); dots = save matmul outputs "
                         "(recompute only elementwise)")
    ap.add_argument("--lm-opt-state", default="f32",
                    choices=["f32", "bf16", "int8"],
                    help="adam moment storage (tables/updaters.py): "
                         "bf16 halves, int8 (blockwise) quarters the "
                         "optimizer-state HBM that bounds the frontier")
    ap.add_argument("--lm-head-chunk", type=int, default=128,
                    help="sequence-chunked tied head + CE: the [B,T,vocab]"
                         " logits never materialize (models/transformer.py"
                         " nll_chunked); 0 = plain head")
    ap.add_argument("--wd-slots", type=int, default=1 << 22)
    # mf: ML-20M-scale id spaces (138k users / 27k movies, next pow2)
    ap.add_argument("--mf-users", type=int, default=1 << 18)
    ap.add_argument("--mf-items", type=int, default=1 << 15)
    ap.add_argument("--mf-dim", type=int, default=32)
    # w2v: enwiki-scale vocab, classic SGNS hyperparams
    ap.add_argument("--w2v-vocab", type=int, default=1 << 20)
    ap.add_argument("--w2v-dim", type=int, default=128)
    ap.add_argument("--w2v-neg", type=int, default=5)
    # 512k rows ≈ 0.7s of steady-state pipeline at the measured rate — a
    # 131k-row run finishes in ~0.2s, short enough for tunnel jitter to
    # dominate the reading
    ap.add_argument("--e2e-rows", type=int, default=524288)
    ap.add_argument("--e2e-batch", type=int, default=16384,
                    help="e2e streams this batch size (decoupled from "
                         "--batch so the pipeline sees many batches)")
    args = ap.parse_args()
    if args.probe_window is None:
        # resolve the env default ONCE so child forwarding and both
        # probe call sites agree on the operator's choice (a literal
        # fallback at the fork site would ignore MINIPS_PROBE_WINDOW=0)
        args.probe_window = _default_probe_window()
    if args.chain < 1 or args.reps < 1:
        ap.error("--chain and --reps must be >= 1")
    if args.lm_dim % 64 or args.lm_dim < 64:
        # heads = lm_dim/64 (64-dim heads, MXU-shaped); a non-multiple
        # would derive a head count that doesn't divide the model dim
        ap.error("--lm-dim must be a positive multiple of 64")
    if args.lm_head_chunk and args.lm_seq % args.lm_head_chunk:
        # the chunked head scans whole chunks; with a default chunk of
        # 128 an odd --lm-seq must not crash the suite — drop to the
        # plain head and say so
        print(f"bench: --lm-seq {args.lm_seq} not divisible by "
              f"--lm-head-chunk {args.lm_head_chunk}; using the plain "
              "head (--lm-head-chunk 0)", file=sys.stderr)
        args.lm_head_chunk = 0

    if args.profile and args.suite not in ("lrmlp", "lm", "wd", "mf",
                                           "w2v"):
        # only the chained-scan suites run under _chain_timed and can
        # capture; ps is jax-free, e2e times a streaming loop, and "all"
        # forks children without forwarding the flag
        print(f"bench: --profile is ignored for --suite {args.suite} "
              "(profilable: lrmlp, lm, wd, mf, w2v)", file=sys.stderr)
        args.profile = None

    if args.suite == "ps":
        # control-plane suite: loopback subprocesses, no chip, no jax in
        # this process — runs before (and independent of) the TPU probe
        _emit({"ps": bench_ps(args)}, False, "cpu-loopback(control-plane)",
              None, None)
        return 0

    if args.suite == "ps_tpu":
        # the PS wire + jitted worker compute row: rank 0 of the worker
        # job takes the chip IF the probe says it is alive; this parent
        # still never initializes jax
        chip = not args.cpu and _tpu_available(args.probe_window)
        _emit({"ps_tpu": bench_ps_tpu(args, force_cpu=not chip)}, False,
              ("mixed(rank0-tpu,peers-cpu)" if chip
               else "cpu-loopback(tpu-unavailable)"), None, None)
        return 0

    if args.suite == "all":
        # each suite in a FRESH child process, the parent NEVER touching
        # JAX: (a) measured in-process interference — later suites read up
        # to 4x slow after earlier suites' compiled programs/buffers
        # accumulate (e2e isolated 727-872k vs 202-237k run last
        # in-process on the same chip); (b) on standard TPU VMs libtpu is
        # exclusive per process, so a parent holding the chip would starve
        # every child into CPU fallback.
        return _run_all(args)

    device_note = "tpu"
    if not args.cpu and not _tpu_available(args.probe_window):
        print("bench: TPU unresponsive within probe window; "
              "falling back to CPU mesh", file=sys.stderr)
        args.cpu = True
        device_note = "cpu-fallback(tpu-unresponsive)"
    if args.cpu:
        import os
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
        if device_note == "tpu":
            device_note = "cpu"
        # CPU runs shrink the shapes: this path exists to validate the
        # harness, never to publish numbers (vs_baseline stays null)
        args.batch = min(args.batch, 2048)
        args.e2e_batch = min(args.e2e_batch, 2048)
        args.lm_batch = min(args.lm_batch, 8)
        args.wd_slots = min(args.wd_slots, 1 << 18)
        args.mf_users = min(args.mf_users, 1 << 14)
        args.mf_items = min(args.mf_items, 1 << 12)
        args.w2v_vocab = min(args.w2v_vocab, 1 << 14)
        args.e2e_rows = min(args.e2e_rows, 16384)
        args.lm_seq = min(args.lm_seq, 256)
        args.lm_dim = min(args.lm_dim, 512)
        args.lm_depth = min(args.lm_depth, 4)
        args.chain = min(args.chain, 4)
        args.reps = min(args.reps, 2)
    import jax

    from minips_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()  # warm rounds skip the 20-40s first TPU compile

    n_chips = len(jax.devices())
    on_tpu = device_note == "tpu"
    peak = _peak_for(jax.devices()[0]) if on_tpu else None

    global _PROFILE_DIR
    _PROFILE_DIR = args.profile
    profile_t0 = time.time()

    suites = {}
    want = [args.suite]
    if "lrmlp" in want:
        suites["lrmlp"] = bench_lrmlp(args, n_chips, peak)
    if "lm" in want:
        suites["lm"] = bench_lm(args, n_chips, peak)
    if "wd" in want:
        suites["wd"] = bench_wd(args, n_chips, peak)
    if "mf" in want:
        suites["mf"] = bench_mf(args, n_chips, peak)
    if "w2v" in want:
        suites["w2v"] = bench_w2v(args, n_chips, peak)
    if "e2e" in want:
        suites["e2e"] = bench_e2e(args, n_chips)

    if _PROFILE_DIR and suites:
        import os

        from minips_tpu.utils.trace_analysis import (latest_trace_file,
                                                     summarize)
        # one suite per invocation when profiling; the table lands on it.
        # Freshness-gate: a pre-existing trace in a reused dir (or a
        # swallowed start_trace failure) must not be misattributed to
        # this run as its profile.
        newest = latest_trace_file(_PROFILE_DIR)
        if newest is not None and os.path.getmtime(newest) >= profile_t0:
            prof = summarize(_PROFILE_DIR, top=12)
        else:
            prof = {"error": "no trace captured during this run "
                             "(profiler unavailable on this backend?)"}
        suites[next(iter(suites))]["profile"] = prof

    # only the lrmlp suite measures the BASELINE metric; a run that skipped
    # it must not label another suite's rate as LR+MLP or ratio it against
    # the samples/sec north-star (that would be weak-#7 all over again);
    # off-TPU numbers are not comparable to the TPU target: vs stays null
    _emit(suites, on_tpu, device_note,
          getattr(jax.devices()[0], "device_kind", "?"),
          (peak / 1e12) if peak else None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
