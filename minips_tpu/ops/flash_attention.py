"""Flash attention — fused blockwise causal attention for the LM family.

The reference has no attention at all (SURVEY.md §2.2: LR/MLP/MF/W&D/w2v);
the LM/transformer family is this rebuild's beyond-parity long-context
capability, and this module is its single-chip hot op. Two implementations
of the same exact math (softmax(QK^T)V, never materializing the [T, T]
score matrix in HBM):

- ``blockwise_attention`` — pure jnp, ``lax.scan`` over K/V chunks with
  online-softmax carry. Runs anywhere (CPU tests, TPU), differentiable by
  AD through the scan, O(T·block_k) live scores. This is the oracle-exact
  portable path and the backward function for the kernel below.

- ``flash_attention`` — Pallas TPU kernels. Forward: grid (batch, head,
  Q blocks, K blocks) with the K sweep innermost; the float32 online-
  softmax state (running max m, normalizer l, accumulator acc) lives in
  VMEM scratch across the sweep, blocks are pipelined HBM→VMEM by Pallas,
  scores exist only in VMEM, and the per-row logsumexp is written out for
  the backward. Backward (``jax.custom_vjp``): two kernels that recompute
  p = exp(s − lse) per block — dQ accumulates over the K sweep, dK/dV over
  the transposed Q sweep — so training memory stays O(T) and the [T, T]
  matrix never exists in either pass. Causal runs skip fully-masked blocks
  in all three kernels.

Measured on the one real chip here (2026-07-29, bf16, B=2 H=8 D=64,
T=8192): forward 5.8ms vs 12.4ms XLA full-scores; fwd+bwd 21ms vs 40ms;
end-to-end LM training (apps/lm_example --attn flash) 1.5x tokens/sec at
T=8192, and T=32768 works where full scores OOM HBM.

Layout matches the rest of the stack: q/k/v are ``[B, T, H, D]`` (the
ring-attention convention, parallel/ring_attention.py). The kernel wants
the sequence contiguous per (batch, head), so it transposes to
``[B, H, T, D]`` at the jit boundary — XLA fuses the transposes into the
surrounding program.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

import jax.numpy as jnp

from minips_tpu.utils import jaxcompat
from minips_tpu.utils.jaxcompat import axis_size as _axis_size

try:  # pallas imports can fail on exotic backends; degrade to blockwise
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = -1e30  # finite mask value (matches ring_attention) — avoids
                  # -inf arithmetic NaNs on fully-masked rows


def _pcast_varying(x, axes):
    """pcast x to varying over exactly the axes it isn't already varying
    over (pcast rejects varying→varying)."""
    have = getattr(jaxcompat.typeof(x), "vma", frozenset())
    need = tuple(a for a in axes if a not in have)
    return jaxcompat.pcast(x, need, to="varying") if need else x


def gqa_group_size(num_q_heads: int, num_kv_heads: int) -> int:
    """Q-heads per KV head (grouped-query attention). 1 = classic MHA,
    num_q_heads = MQA. Raises unless kv divides q."""
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"GQA needs kv_heads ({num_kv_heads}) to divide q heads "
            f"({num_q_heads})")
    return num_q_heads // num_kv_heads


def _expand_kv(q, k, v):
    """Repeat K/V heads up to Q's head count for the pure-jnp paths.
    This forfeits GQA's memory saving (it exists only for oracle/fallback
    exactness off-TPU); the Pallas kernels instead map each q-head's
    block index onto its kv head and never materialize the repeat."""
    g = gqa_group_size(q.shape[2], k.shape[2])
    if g == 1:
        return k, v
    return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)


# --------------------------------------------------------------- blockwise
def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_k: int = 256,
    q_off=0,
    k_off=0,
    return_lse: bool = False,
):
    """Exact attention, scanning K/V in chunks of ``block_k``.

    q/k/v: [B, T, H, D]. Equals softmax(QK^T·scale)V to float tolerance;
    peak score memory is [B, Tq, block_k, H] instead of [B, Tq, Tk, H].
    Ragged K tails are padded and masked, preserving that bound.

    ``q_off``/``k_off`` shift causal masking to global positions (the ring
    path passes each shard's sequence offset); ``return_lse=True`` also
    returns the per-row logsumexp [B, Tq, H] for shard merging. This is
    the pure-jnp twin of the Pallas kernels.
    """
    B, Tq, H, D = q.shape
    k, v = _expand_kv(q, k, v)   # GQA: exact repeat on this oracle path
    Tk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    bk = min(block_k, Tk)
    pad = (-Tk) % bk  # ragged tail: pad K/V and mask — never one full-width
    if pad:           # chunk, which would void the O(T*block_k) bound
        zeros = jnp.zeros((B, pad, H, D), k.dtype)
        k = jnp.concatenate([k, zeros], axis=1)
        v = jnp.concatenate([v, zeros], axis=1)
    masked = causal or pad
    nk = (Tk + pad) // bk
    qf = q.astype(jnp.float32)
    kc = k.astype(jnp.float32).reshape(B, nk, bk, H, D)
    vc = v.astype(jnp.float32).reshape(B, nk, bk, H, D)
    q_pos = q_off + jnp.arange(Tq)

    def fold(carry, blk):
        o, m, l = carry
        k_blk, v_blk, j = blk
        s = jnp.einsum("bqhd,bkhd->bqkh", qf, k_blk) * scale
        if masked:
            k_local = j * bk + jnp.arange(bk)
            keep = k_local[None, :] < Tk  # padding keys attend to nothing
            if causal:
                keep = keep & (q_pos[:, None] >= (k_off + k_local)[None, :])
            s = jnp.where(keep[None, :, :, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=2))        # [B, Tq, H]
        p = jnp.exp(s - m_new[:, :, None, :])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=2)
        o = o * alpha[:, :, :, None] + jnp.einsum("bqkh,bkhd->bqhd", p, v_blk)
        return (o, m_new, l), None

    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    m0 = jnp.full((B, Tq, H), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Tq, H), jnp.float32)
    # Inside shard_map, fresh carries are axis-invariant while the folded
    # values vary over the mesh — pcast keeps the scan carry type fixed
    # (same VMA discipline as ring_attention_local).
    vma = tuple(sorted(_vma_of(q, k, v, q_off, k_off)))
    o0, m0, l0 = (_pcast_varying(x, vma) for x in (o0, m0, l0))
    (o, m, l), _ = jax.lax.scan(
        fold, (o0, m0, l0),
        (kc.swapaxes(0, 1), vc.swapaxes(0, 1), jnp.arange(nk)))
    l_safe = jnp.maximum(l, 1e-30)
    out = (o / l_safe[..., None]).astype(q.dtype)
    if return_lse:
        return out, m + jnp.log(l_safe)
    return out


# ----------------------------------------------------------- pallas kernel
#
# All three kernels mask by GLOBAL positions: row q_off + (local index),
# col k_off + (local index). Plain causal attention passes offsets (0, 0);
# ring flash attention (ring_flash_attention_local) passes each shard's
# sequence offsets so the same kernels compute the diagonal, kept, and
# fully-masked ring steps. Offsets arrive as (1,) int32 arrays in SMEM.

def _mask_scores(s, masked, i, j, bq, bk, q_off, k_off):
    if not masked:
        return s
    q_pos = q_off + i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_off + j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _block_live(masked, i, j, bq, bk, q_off, k_off):
    """False only for blocks that the global causal mask kills entirely —
    skip their matmuls (the block DMA still happens; compute dominates)."""
    if not masked:
        return True
    return k_off + j * bk <= q_off + (i + 1) * bq - 1


def _flash_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                  acc_ref, m_ref, l_ref, *, scale, masked, num_k):
    # Grid (B, H, nQ, nK), K innermost and sequential on TPU: the online-
    # softmax state for one Q block lives in VMEM scratch across the nK
    # sweep. Blocks: q/o [1, 1, bq, D]; k/v [1, 1, bk, D]; lse [1, 1, bq, 1].
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(_block_live(masked, i, j, bq, bk, q_off, k_off))
    def _fold():
        # dots run in the INPUT dtype (bf16 inputs → bf16 MXU rate, half
        # the VMEM traffic) with f32 accumulation; all online-softmax
        # state stays f32. f32 inputs behave exactly as before.
        qb = q_ref[0, 0, :, :]
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, masked, i, j, bq, bk, q_off, k_off)
        m = m_ref[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))  # [bq, 1]
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = (acc_ref[:] * alpha
                      + jnp.dot(p.astype(vb.dtype), vb,
                                preferred_element_type=jnp.float32))
        m_ref[:] = m_new

    @pl.when(j == num_k - 1)
    def _write():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0, :, :] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # true logsumexp per row — the backward recomputes p = exp(s - lse),
        # and the ring merge weights shards by exp(lse_s - lse_total)
        lse_ref[0, 0, :, 0] = (m_ref[:] + jnp.log(l_safe))[:, 0]


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _vma_of(*xs):
    # Inside shard_map the output type must declare which mesh axes it
    # varies over (VMA tracking); it varies exactly where the inputs do.
    vma = frozenset()
    for x in xs:
        vma = vma | getattr(jaxcompat.typeof(x), "vma", frozenset())
    return vma


def _flash_forward(q, k, v, q_off, k_off, masked, scale, block_q, block_k,
                   interpret):
    """[B, T, H, D] in/out; kernel runs on [B, H, T, D]. K/V may carry
    fewer heads (GQA): each q-head's K/V block index maps onto kv head
    h // g — the repeat never materializes, so KV HBM traffic shrinks by
    the group factor."""
    B, Tq, H, D = q.shape
    g = gqa_group_size(H, k.shape[2])
    Tk = k.shape[1]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    grid = (B, H, Tq // bq, Tk // bk)
    vma = _vma_of(q, k, v, q_off, k_off)
    offs = (jnp.asarray(q_off, jnp.int32).reshape(1),
            jnp.asarray(k_off, jnp.int32).reshape(1))
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, masked=masked,
                          num_k=Tk // bk),
        grid=grid,
        in_specs=[
            _smem_spec(), _smem_spec(),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j: (b, h // g, j, 0)),
            pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j: (b, h // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jaxcompat.sds((B, H, Tq, D), q.dtype, vma=vma),
            jaxcompat.sds((B, H, Tq, 1), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),   # acc
            pltpu.VMEM((bq, 1), jnp.float32),   # running max m
            pltpu.VMEM((bq, 1), jnp.float32),   # normalizer l
        ],
        interpret=interpret,
    )(*offs, qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


def _flash_bwd_dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                         lse_ref, dvec_ref, dq_ref, dq_acc, *, scale,
                         masked, num_k):
    # Grid (B, H, nQ, nK), K innermost; dQ for one Q block accumulates in
    # scratch across the K sweep. p is recomputed from the saved
    # logsumexp — the [T, T] matrix never exists.
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    i, j = pl.program_id(2), pl.program_id(3)
    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_block_live(masked, i, j, bq, bk, q_off, k_off))
    def _fold():
        # native-dtype dots, f32 accumulation/softmax state (see _fold in
        # _flash_kernel); ds is cast back to the input dtype for its dot
        qb = q_ref[0, 0, :, :]
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        dob = do_ref[0, 0, :, :]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, masked, i, j, bq, bk, q_off, k_off)
        p = jnp.exp(s - lse_ref[0, 0, :, :])            # [bq, bk] f32
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dvec_ref[0, 0, :, :]) * scale
        dq_acc[:] = dq_acc[:] + jnp.dot(
            ds.astype(kb.dtype), kb, preferred_element_type=jnp.float32)

    @pl.when(j == num_k - 1)
    def _write():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                          lse_ref, dvec_ref, dk_ref, dv_ref, dk_acc,
                          dv_acc, *, scale, masked, num_q, q_per_kv):
    # Grid (B, Hk, nK, q_per_kv*nQ), the combined (group q-head, Q block)
    # sweep innermost; dK/dV for one KV-head K block accumulate in scratch
    # across BOTH — under GQA every kv head receives gradient from all
    # q_per_kv q-heads of its group (the transposed iteration of dq).
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    j, t = pl.program_id(2), pl.program_id(3)   # j: K block
    i = jax.lax.rem(t, num_q)                   # i: Q block within head
    q_off, k_off = qoff_ref[0], koff_ref[0]

    @pl.when(t == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_block_live(masked, i, j, bq, bk, q_off, k_off))
    def _fold():
        qb = q_ref[0, 0, :, :]
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        dob = do_ref[0, 0, :, :]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, masked, i, j, bq, bk, q_off, k_off)
        p = jnp.exp(s - lse_ref[0, 0, :, :])            # [bq, bk] f32
        dv_acc[:] = dv_acc[:] + jnp.dot(
            p.T.astype(dob.dtype), dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dvec_ref[0, 0, :, :]) * scale
        dk_acc[:] = dk_acc[:] + jnp.dot(
            ds.T.astype(qb.dtype), qb, preferred_element_type=jnp.float32)

    @pl.when(t == num_q * q_per_kv - 1)
    def _write():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, q_off, k_off, g_out, lse, dvec, masked, scale,
                    block_q, block_k, interpret):
    """dQ/dK/dV via the two backward kernels; [B, T, H, D] layout.
    ``dvec`` is [B, H, Tq, 1] — rowsum(dO*O) minus the lse cotangent.
    Under GQA dk/dv come back at the kv head count."""
    B, Tq, H, D = q.shape
    Hk = k.shape[2]
    g = gqa_group_size(H, Hk)
    Tk = k.shape[1]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    qt, kt, vt, dot = (x.transpose(0, 2, 1, 3) for x in (q, k, v, g_out))
    vma = _vma_of(q, k, v, q_off, k_off, g_out)
    offs = (jnp.asarray(q_off, jnp.int32).reshape(1),
            jnp.asarray(k_off, jnp.int32).reshape(1))

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, D),
                           lambda b, h, i, j: (b, h // g, j, 0))
    row_spec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, masked=masked,
                          num_k=Tk // bk),
        grid=(B, H, Tq // bq, Tk // bk),
        in_specs=[_smem_spec(), _smem_spec(),
                  q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jaxcompat.sds((B, H, Tq, D), q.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*offs, qt, kt, vt, dot, lse, dvec)

    # transposed grid: K outer, (group q-head, Q block) inner — grid dim 1
    # walks KV heads, the q-head within the group rides the inner sweep
    nq = Tq // bq
    q_spec_t = pl.BlockSpec(
        (1, 1, bq, D), lambda b, hk, j, t: (b, hk * g + t // nq, t % nq, 0))
    kv_spec_t = pl.BlockSpec((1, 1, bk, D),
                             lambda b, hk, j, t: (b, hk, j, 0))
    row_spec_t = pl.BlockSpec(
        (1, 1, bq, 1), lambda b, hk, j, t: (b, hk * g + t // nq, t % nq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          masked=masked, num_q=nq, q_per_kv=g),
        grid=(B, Hk, Tk // bk, g * nq),
        in_specs=[_smem_spec(), _smem_spec(),
                  q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[
            jaxcompat.sds((B, Hk, Tk, D), k.dtype, vma=vma),
            jaxcompat.sds((B, Hk, Tk, D), v.dtype, vma=vma),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*offs, qt, kt, vt, dot, lse, dvec)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


def _int_zero_cotangent(x):
    import numpy as np

    return np.zeros(jnp.shape(x), jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_with_lse(q, k, v, q_off, k_off, masked, scale, block_q, block_k,
                    interpret):
    """Core primitive: (out, lse) with global-offset causal masking.
    The lse output is a first-class differentiable result — the ring merge
    consumes it, so its cotangent must flow (see _flash_with_lse_bwd)."""
    return _flash_forward(q, k, v, q_off, k_off, masked, scale, block_q,
                          block_k, interpret)


def _flash_with_lse_fwd(q, k, v, q_off, k_off, masked, scale, block_q,
                        block_k, interpret):
    out, lse = _flash_forward(q, k, v, q_off, k_off, masked, scale,
                              block_q, block_k, interpret)
    return (out, lse), (q, k, v, q_off, k_off, out, lse)


def _flash_with_lse_bwd(masked, scale, block_q, block_k, interpret, res,
                        gs):
    q, k, v, q_off, k_off, out, lse = res
    g, g_lse = gs
    # ds = p * (dp - rowsum(dO*O) + g_lse): the lse cotangent enters the
    # softmax-jacobian row term with opposite sign to D_i, so both ride
    # the same dvec input of the kernels (d lse / d s_k = p_k).
    dvec = (jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)[..., None]
            - g_lse.astype(jnp.float32))                 # [B, H, Tq, 1]
    dq, dk, dv = _flash_backward(
        q, k, v, q_off, k_off, g, lse, dvec, masked, scale, block_q,
        block_k, interpret)
    return (dq, dk, dv, _int_zero_cotangent(q_off),
            _int_zero_cotangent(k_off))


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    zero = jnp.zeros((), jnp.int32)
    return _flash_with_lse(q, k, v, zero, zero, causal, scale, block_q,
                           block_k, interpret)[0]


def kernel_supported(q_shape, k_shape, block_q: int, block_k: int) -> bool:
    """Static shape gate for the Pallas path: block sizes must tile the
    sequence (no ragged tails in the kernel) and D should be lane-friendly."""
    if not _HAS_PALLAS:
        return False
    B, Tq, H, D = q_shape
    Tk = k_shape[1]
    bq, bk = min(block_q, Tq), min(block_k, Tk)
    if q_shape[2] % k_shape[2]:   # GQA: kv heads must divide q heads
        return False
    return Tq % bq == 0 and Tk % bk == 0 and D % 8 == 0


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused attention; same signature/semantics as
    ``ring_attention.reference_attention`` but never materializes the full
    score matrix. Uses the Pallas kernel on TPU (or ``interpret=True``
    anywhere, for tests); otherwise the blockwise scan — both exact.

    Grouped-query attention: K/V may carry fewer heads than Q (kv divides
    q, q-head h reads kv head h // group). The kernel path streams the
    small K/V straight from HBM — traffic and ring wire bytes shrink by
    the group factor; the fallback repeats heads (exact, memory-expanded).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = False
        use_kernel = (kernel_supported(q.shape, k.shape, block_q, block_k)
                      and jax.default_backend() == "tpu")
    else:
        use_kernel = kernel_supported(q.shape, k.shape, block_q, block_k)
    if use_kernel:
        return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return blockwise_attention(q, k, v, causal=causal, scale=scale,
                               block_k=block_k)


# -------------------------------------------------------- ring flash attn
def ring_flash_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Ring attention with the flash kernel doing each step's blockwise
    math — call INSIDE shard_map with the sequence axis sharded along
    ``axis_name`` (drop-in for ring_attention.ring_attention_local).

    Each of the N ring steps runs the offset-masked flash kernel on the
    resident Q shard against the visiting K/V shard (global positions via
    q_off/k_off, so diagonal steps are causal, earlier shards fully kept,
    later shards fully skipped) and returns (out_s, lse_s). Shards merge by
    logsumexp weighting — exact attention over the full sequence. Forward
    per-device memory is O(T/N); training stores each step's visiting K/V
    shard as AD residuals (O(T) per device across the n steps) — wrap the
    caller in jax.checkpoint (the LM family's ``remat=True``) to trade
    that back to O(T/N). K/V rotate one ICI hop per step (ppermute); XLA
    overlaps the hop with the kernel. Gradients flow through the kernels'
    custom VJP at every step.
    """
    n = _axis_size(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    # Pallas path: compiled on TPU, interpreter only if explicitly asked
    # (the interpreter can't track varying-manual-axes, so it only works
    # under check_vma=False — kernel-level tests). Everywhere else the
    # per-step math runs as the pure-jnp offset blockwise scan: same
    # algorithm and f32 softmax state, ordinary AD, no pallas involved.
    # Numerics match exactly for f32 inputs; for bf16 inputs the scan
    # upcasts q/k/v to f32 before its dots while the kernel runs
    # bf16-input dots with f32 accumulation (≤ bf16-rounding apart).
    use_kernel = (kernel_supported(q.shape, k.shape, block_q, block_k)
                  and (interpret is True
                       or (interpret is None
                           and jax.default_backend() == "tpu")))
    interpret = bool(interpret) if interpret is not None else False
    perm = [(i, (i + 1) % n) for i in range(n)]
    # With causal=False no step masks, so the global offsets cannot affect
    # the math — and materializing axis_index here would leave an orphaned
    # partition-id in the lowered module (no path to a manual-sharded
    # operand for sharding propagation to infer {manual} from), which the
    # SPMD partitioner rejects. Only mint r when masking consumes it.
    if causal:
        r = jax.lax.axis_index(axis_name)
        q_off = (r * Tq).astype(jnp.int32)
    else:
        r = jnp.zeros((), jnp.int32)
        q_off = jnp.zeros((), jnp.int32)

    def step_fn(carry, s):
        acc, lse_run, k_cur, v_cur = carry
        src = ((r - s) % n).astype(jnp.int32)     # original owner of k_cur
        if use_kernel:
            o_s, lse_s = _flash_with_lse(
                q, k_cur, v_cur, q_off, src * Tk, causal, scale,
                min(block_q, Tq), min(block_k, Tk), interpret)
            lse_s = lse_s[..., 0].transpose(0, 2, 1)   # -> [B, Tq, H]
        else:
            o_s, lse_s = blockwise_attention(
                q, k_cur, v_cur, causal=causal, scale=scale,
                block_k=block_k, q_off=q_off, k_off=src * Tk,
                return_lse=True)
        lse_new = jnp.logaddexp(lse_run, lse_s)
        acc = (acc * jnp.exp(lse_run - lse_new)[..., None]
               + o_s.astype(jnp.float32)
               * jnp.exp(lse_s - lse_new)[..., None])
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, lse_new, k_nxt, v_nxt), None

    acc0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    lse0 = jnp.full((B, Tq, H), _NEG_INF, jnp.float32)
    # the visiting K/V shards (and, under causal, the axis index r) make
    # every step output vary over the ring axis, so ALL carries must be
    # varying — even when the inputs arrive replicated
    acc0, lse0, k, v = (_pcast_varying(x, (axis_name,))
                        for x in (acc0, lse0, k, v))
    (acc, _, _, _), _ = jax.lax.scan(
        step_fn, (acc0, lse0, k, v), jnp.arange(n))
    return acc.astype(q.dtype)
