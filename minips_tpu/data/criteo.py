"""Criteo display-advertising TSV reader/writer.

The reference family's flagship sparse workload is Wide&Deep / DeepFM on
Criteo-1TB (SURVEY.md §2 "Data loading"; BASELINE.json:10). Line format:

    label \\t I1..I13 (decimal ints, may be empty or negative)
          \\t C1..C26 (8-hex-digit categorical hashes, may be empty)

``read_criteo`` returns the same batch schema the apps and the synthetic
generator use (minips_tpu/data/synthetic.py ``criteo_like``):

- ``y``          [N]      float32 click labels
- ``dense``      [N, 13]  float32 numeric features (missing → 0)
- ``dense_mask`` [N, 13]  float32 presence mask
- ``cat``        [N, 26]  int64 categorical ids, offset ``field << 32`` so
  every column keeps a distinct id space (per-column vocabularies); missing
  values map to the field-offset 0 token. Downstream, SparseTable hashes
  these unbounded ids onto slots (tables/sparse.py ``hash_to_slots``).

A native C++ parser (cpp/criteo_reader.cpp, SURVEY.md §2.1 item 6) is used
transparently when buildable; the pure-Python path is the fallback and the
correctness oracle for it.
"""

from __future__ import annotations

import numpy as np

NUM_DENSE = 13
NUM_CAT = 26


def write_criteo(path: str, y: np.ndarray, dense: np.ndarray,
                 cat: np.ndarray, dense_mask: np.ndarray | None = None) -> None:
    """Write rows in Criteo TSV form (used by tests/synthetic dumps). ``cat``
    entries are written as 8-hex of their low 32 bits; a masked-out dense
    cell (or NaN) is written as an empty field."""
    y = np.asarray(y)
    dense = np.asarray(dense)
    cat = np.asarray(cat)
    with open(path, "w") as f:
        for r in range(len(y)):
            fields = [str(int(y[r]))]
            for j in range(dense.shape[1]):
                v = dense[r, j]
                present = not np.isnan(v) if dense_mask is None \
                    else bool(dense_mask[r, j])
                fields.append(str(int(v)) if present else "")
            for j in range(cat.shape[1]):
                fields.append(format(int(cat[r, j]) & 0xFFFFFFFF, "08x"))
            f.write("\t".join(fields) + "\n")


def _read_python(path: str) -> dict:
    with open(path) as f:
        return _parse_lines(f, where=path)


def _parse_lines(lines, where: str = "<lines>") -> dict:
    """Parse an iterable of Criteo TSV lines (str or bytes) — the one
    Python parsing loop behind both the whole-file and byte-span paths
    (and the correctness oracle for the native parser)."""
    ys, denses, masks, cats = [], [], [], []
    field_offset = np.arange(NUM_CAT, dtype=np.int64) << 32
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode()
        line = line.rstrip("\r\n")
        if not line:
            continue
        parts = line.split("\t")
        # pad short lines so slicing below is uniform
        parts += [""] * (1 + NUM_DENSE + NUM_CAT - len(parts))
        # strict int label (same contract as the native parser's rc=3)
        ys.append(float(int(parts[0])) if parts[0] else 0.0)
        d = np.zeros(NUM_DENSE, np.float32)
        m = np.zeros(NUM_DENSE, np.float32)
        for j, tok in enumerate(parts[1:1 + NUM_DENSE]):
            if tok:
                d[j] = float(int(tok))
                m[j] = 1.0
        cat_toks = parts[1 + NUM_DENSE:1 + NUM_DENSE + NUM_CAT]
        if any(len(tok) > 8 for tok in cat_toks):
            # >8 hex digits would exceed the 32-bit per-field id space
            # (the native parser rejects these too — rc=3)
            raise ValueError(f"categorical token over 8 hex digits in "
                             f"{where!r}")
        c = np.array([int(tok, 16) if tok else 0 for tok in cat_toks],
                     np.int64) | field_offset
        denses.append(d)
        masks.append(m)
        cats.append(c)
    n = len(ys)
    return {
        "y": np.asarray(ys, np.float32),
        "dense": (np.stack(denses) if n else
                  np.zeros((0, NUM_DENSE), np.float32)),
        "dense_mask": (np.stack(masks) if n else
                       np.zeros((0, NUM_DENSE), np.float32)),
        "cat": (np.stack(cats) if n else np.zeros((0, NUM_CAT), np.int64)),
    }


def read_criteo(path: str, use_native: bool = True,
                shared: bool = False) -> dict:
    """Returns dict(y, dense, dense_mask, cat) — see module docstring.
    ``shared=True``: under the launcher, only the host's local leader
    parses; colocated processes mmap the same copy (data/shm_store.py)."""
    if shared:
        from minips_tpu.data.shm_store import make_tag, shared_load

        tag = make_tag("criteo", path)
        return shared_load(tag, lambda: read_criteo(
            path, use_native=use_native, shared=False))
    if use_native:
        try:
            from minips_tpu.data.native import read_criteo_native

            out = read_criteo_native(path)
            if out is not None:
                return out
        except ImportError:
            pass
    return _read_python(path)


def parse_criteo_chunk(data: bytes, use_native: bool = True,
                       where: str = "<bytes>") -> dict:
    """Parse a chunk of whole Criteo TSV lines already in memory. Native
    fast path (cpp criteo_parse_mem) with the Python line parser as
    fallback/oracle."""
    if use_native:
        try:
            from minips_tpu.data.native import parse_criteo_bytes

            out = parse_criteo_bytes(data, where=where)
            if out is not None:
                return out
        except ImportError:
            pass
    return _parse_lines(data.splitlines(), where=where)


def stream_criteo_batches(path: str, batch_size: int, *,
                          chunk_bytes: int = 8 << 20,
                          use_native: bool = True, prefetch: int = 2,
                          transform=None, stats: dict | None = None):
    """Streaming ingestion: a producer thread reads the file ONCE,
    sequentially, in ~``chunk_bytes`` line-aligned chunks and parses each
    straight from memory while the consumer trains on earlier batches —
    parse overlaps compute, the first batch exists after one chunk, and
    the working set is one chunk, never the file (SURVEY.md §7.4.4; the
    Criteo-1TB posture). Yields dict batches of exactly ``batch_size``
    rows (tails carry across chunks; a final short batch is dropped — pass
    ``stats={}`` to read back ``stats["dropped_rows"]`` after exhaustion,
    the repo's no-silent-caps convention).
    ``transform(block_dict) -> block_dict`` runs ON THE PRODUCER THREAD
    (e.g. log_transform of dense), keeping that cost off the training
    thread too. Abandoning the generator (close/GC/exception) stops the
    producer promptly — it never blocks forever on a full queue."""
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=max(1, prefetch))
    stop = threading.Event()
    _SENTINEL = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.25)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            with open(path, "rb") as f:
                tail = b""
                while not stop.is_set():
                    chunk = f.read(chunk_bytes)
                    if not chunk:
                        break
                    chunk = tail + chunk
                    nl = chunk.rfind(b"\n")
                    if nl < 0:  # no complete line yet; keep accumulating
                        tail = chunk
                        continue
                    tail = chunk[nl + 1:]
                    d = parse_criteo_chunk(chunk[: nl + 1],
                                           use_native=use_native,
                                           where=path)
                    if not put(d if transform is None else transform(d)):
                        return
                if tail and not stop.is_set():
                    d = parse_criteo_chunk(tail, use_native=use_native,
                                           where=path)
                    if not put(d if transform is None else transform(d)):
                        return
            put(_SENTINEL)
        except BaseException as e:  # surface parse errors to the consumer
            put(e)

    threading.Thread(target=produce, daemon=True).start()

    # linear batching: one concat of the (< batch_size) leftover per
    # chunk; yielded batches are views into the chunk's arrays
    buf = None
    pos = 0
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            if buf is None or pos >= len(buf["y"]):
                buf, pos = item, 0
            else:
                buf = {k: np.concatenate([buf[k][pos:], item[k]])
                       for k in buf}
                pos = 0
            n = len(buf["y"])
            while pos + batch_size <= n:
                yield {k: v[pos:pos + batch_size] for k, v in buf.items()}
                pos += batch_size
        if stats is not None:  # rows short of one final batch, dropped
            stats["dropped_rows"] = (len(buf["y"]) - pos) if buf else 0
    finally:
        stop.set()


def log_transform(dense: np.ndarray,
                  mask: np.ndarray | None = None) -> np.ndarray:
    """Standard Criteo numeric preprocessing: ``log1p(max(x, 0))``, with
    masked-out (missing) cells staying 0. Negative raw values (I2 can be
    −1..−3) clamp to 0 before the log."""
    out = np.log1p(np.maximum(np.asarray(dense, np.float32), 0.0))
    if mask is not None:
        out = out * np.asarray(mask, np.float32)
    return out
