"""CollectiveSSPPS — the consistency axis over the FLAGSHIP workload.

``train/ssp_spmd.py``'s CollectiveSSP proves the north-star clause ("the
consistency controller gates XLA collective barriers", BASELINE.json:5)
on a dense LR table; this module takes the same axis to the workloads the
reference is actually about (SURVEY §7.4.1 + §2.2): W&D/DeepFM's hashed
SparseTables + dense deep tower (``PSTrainStep``), i.e. sparse embedding
PS shards under BSP/SSP/ASP.

The one structural problem beyond dense CSSP: a sparse table's
cross-process delta is TABLE-shaped if merged densely — 2^26 slots of
Criteo embeddings cannot ride a per-sync all-reduce. But each process
only ever touches the slots its batches hashed to, so the honest merge is
ROW-SPARSE:

- every process accumulates its touched slot ids host-side (the same
  ``hash_to_slots_np`` twin the sharded PS routes with — bit-identical
  to the device hash by test);
- at each sync round the processes allgather their touched-id arrays
  over the control bus (``comm.bus.BlobExchange`` — host wire, sized by
  batch rows x sync_every, never by the table) and compute the same
  sorted UNION;
- ONE ``[C, row]`` delta block per table leaf (embedding + optimizer
  rows) rides the collective plane (``SyncPlane.allreduce_sum`` — the
  psum's replica groups cross the process boundary), where C = the
  union size rounded to a power of two. Traffic is O(touched-rows x
  dim), never O(num_slots x dim) — the same batch-sized-traffic
  invariant tests/test_sharded_traffic.py pins for the pull/push plane.

Merge semantics per leaf (the additive replicated-PS rule, applied to
rows): ``new = base + Σ_p (leaf_p − base)`` over the union rows. Rows
touched by nobody are equal to base on every replica already, so the
union merge is EXACT vs a dense merge. For the OPTIMIZER rows:

- sgd has no state — exact;
- adagrad accumulators are sums of squared gradients, an order-free
  additive quantity — the merged accumulator is EXACTLY the accumulator
  a centralized server would hold after the same pushes;
- adam rows (m/v EMAs + per-row step counts) merge additively too: the
  step counts are exact totals, the moments are the local-SGD-family
  approximation documented in docs/consistency.md (same honesty note as
  the dense-table moments).

The deep tower (DenseTable) syncs exactly like CollectiveSSP's dense
vector, including the same optimizer-state stance (see
``opt_sync`` there / docs/consistency.md).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from minips_tpu.comm.bus import BlobExchange
from minips_tpu.consistency.gate import publish_clock
from minips_tpu.parallel.mesh import DATA_AXIS
from minips_tpu.tables.dense import DenseTable
from minips_tpu.tables.sparse import SparseTable, hash_to_slots_np, next_pow2
from minips_tpu.train.ssp_spmd import (SyncPlane, avg_table_opt_state,
                                        check_avg_opt_sync_supported,
                                        is_avg_leaf, make_control,
                                        staleness_for)

__all__ = ["CollectiveSSPPS", "sync_block_rows"]

PyTree = Any


def sync_block_rows(union_size: int, n_local: int) -> int:
    """Rows of the per-sync delta block: the union size rounded up to a
    power of two (keeps the retrace count small — the jitted merge
    recompiles per shape) and then up to a MULTIPLE of ``n_local``
    (shard_map over the local mesh axis needs even divisibility; a
    6-device host would otherwise get C=8 and abort in the sharding
    check, since next_pow2 is only divisible by non-power-of-two device
    counts by luck)."""
    c = max(next_pow2(int(union_size)), int(n_local))
    return -(-c // int(n_local)) * int(n_local)


class CollectiveSSPPS:
    """Local fused PSTrainStep per process; staleness-gated row-sparse
    collective syncs for its sparse tables, vector syncs for its dense
    tables.

    Parameters
    ----------
    build_fn: ``(local_mesh) -> (ps, tables)`` — constructs the fused
        step and its tables ON THE GIVEN MESH (each process's own
        devices). ``tables`` is a name->table dict; DenseTable and
        SparseTable entries are synced, anything else refuses loudly.
        Every process must build identical tables (same seeds) — the
        additive merge assumes a common base.
    staleness / sync_every / bus / monitor: as CollectiveSSP. The bus is
        REQUIRED multi-process: both the clock gossip and the touched-row
        union exchange ride it.
    """

    def __init__(
        self,
        build_fn: Callable,
        *,
        staleness: float = 0,
        sync_every: int = 1,
        bus=None,
        monitor=None,
        gate_timeout: float = 60.0,
        exchange_timeout: float = 120.0,
        opt_sync: str = "local",
    ):
        if sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        if opt_sync not in ("local", "avg"):
            raise ValueError(f"opt_sync must be 'local' or 'avg', got "
                             f"{opt_sync!r}")
        self.opt_sync = opt_sync
        self.staleness = staleness
        self.sync_every = int(sync_every)
        self.nprocs = jax.process_count()
        if self.nprocs > 1 and bus is None:
            raise ValueError(
                "CollectiveSSPPS needs the control bus in multi-process "
                "runs: clock gossip AND the touched-row union exchange "
                "ride it (pass bus= from launch.init_from_env)")
        # register the blob handler BEFORE build_fn: a fast peer may
        # publish its first union while we are still compiling in
        # build_fn, and pub/sub drops frames with no handler (the
        # exchange also re-publishes while waiting, so either side of
        # the race is covered)
        self.exchange = (BlobExchange(bus, self.nprocs)
                         if bus is not None and self.nprocs > 1 else None)

        self.plane = SyncPlane()
        self.local_mesh = self.plane.local_mesh
        self.sync_mesh = self.plane.mesh
        self.ps, tables = build_fn(self.local_mesh)
        for name, t in tables.items():
            if not isinstance(t, (DenseTable, SparseTable)):
                raise TypeError(f"table {name!r} is {type(t).__name__}; "
                                "CollectiveSSPPS syncs DenseTable and "
                                "SparseTable state only")
        self.dense = {k: t for k, t in tables.items()
                      if isinstance(t, DenseTable)}
        self.sparse = {k: t for k, t in tables.items()
                       if isinstance(t, SparseTable)}
        if opt_sync == "avg":
            for t in self.dense.values():
                check_avg_opt_sync_supported(t)
            # sparse opt ROWS already merge additively in _sync_sparse —
            # exact for adagrad (order-free sums), documented heuristic
            # for adam moments; 'avg' only changes the DENSE tables
        for name, t in self.sparse.items():
            if self.ps.key_fns.get(name) is None:
                raise ValueError(
                    f"sparse table {name!r} has no key_fn on the fused "
                    "step — the host-side touched-slot tracking needs it")

        # ---- base snapshots (params = base + Σ deltas across procs) --
        self._copy = jax.jit(jnp.copy)
        self._sub = jax.jit(lambda a, b: a - b)
        self._add = jax.jit(lambda a, b: a + b)
        self._dense_base = {k: self._copy(t.params)
                            for k, t in self.dense.items()}
        self._sparse_base = {
            k: {ln: self._copy(leaf) for ln, leaf in self._leaves(t)}
            for k, t in self.sparse.items()}

        # ---- row-sparse merge programs (retrace per union size C) ----
        self._rep_sharding = NamedSharding(self.local_mesh, P())
        vec_sharding = NamedSharding(self.local_mesh, P(DATA_AXIS))

        def rows_delta(cur, base, idx):
            # idx is padded to C with num_slots (out of bounds): fill-0
            # gathers make padding rows contribute nothing to the psum
            d = (cur.at[idx].get(mode="fill", fill_value=0)
                 - base.at[idx].get(mode="fill", fill_value=0))
            return d.reshape(-1)

        self._rows_delta = jax.jit(rows_delta, out_shardings=vec_sharding)
        self._apply_cache: dict = {}

        # ---- host-side control plane -----------------------------------
        self.clock = 0
        self.sync_rounds = 0
        self._synced_at = 0
        self._monitor = monitor
        self._xt = float(exchange_timeout)
        self.gossip, self._gate = make_control(
            bus, self.nprocs, staleness, monitor=monitor,
            timeout=gate_timeout)
        self._touched: dict[str, set] = {k: set() for k in self.sparse}
        self.sync_rows_max = 0       # largest padded union C seen
        self.union_wire_bytes = 0    # host-wire bytes of the id exchange
        self._last_emb_len = 0       # C*dim of the last emb merge (HLO)

    # -------------------------------------------------------------- helpers
    @staticmethod
    def _leaves(t: SparseTable):
        """(name, array) pairs of a sparse table's row-indexed state."""
        return [("emb", t.emb)] + [(k, getattr(t, k))
                                   for k in t._OPT_KEYS[t.updater]]

    def _apply_for(self, sharding):
        """Jitted (cur, base, idx, merged) -> (cur', base') preserving the
        leaf's sharding; cached per sharding (retraces per shape)."""
        fn = self._apply_cache.get(sharding)
        if fn is None:
            def rows_apply(cur, base, idx, merged_flat):
                rows = merged_flat.reshape((idx.shape[0],) + cur.shape[1:])
                new_rows = base.at[idx].get(mode="fill", fill_value=0) \
                    + rows
                # out-of-bounds padding indices DROP: padding writes
                # nothing, real rows land once (the union is unique)
                return (cur.at[idx].set(new_rows, mode="drop"),
                        base.at[idx].set(new_rows, mode="drop"))

            fn = jax.jit(rows_apply, out_shardings=(sharding, sharding))
            self._apply_cache[sharding] = fn
        return fn

    # ------------------------------------------------------------ metrics
    @property
    def gate_waits(self) -> int:
        return self._gate.gate_waits if self._gate else 0

    @property
    def max_skew_seen(self) -> int:
        return self._gate.max_skew_seen if self._gate else 0

    def sync_hlo(self) -> str:
        """HLO of the LAST embedding-row merge — union-sized by
        construction; smokes assert it contains an all-reduce whose
        operand is C*dim elements, not num_slots*dim."""
        if not self._last_emb_len:
            raise RuntimeError("no row merge has run yet")
        return self.plane.sync_hlo(self._last_emb_len)

    # ------------------------------------------------------------------ api
    def step(self, batch) -> float:
        """One LOCAL fused step on my batch rows, touched-slot tracking,
        clock tick, SSP gate, then (at sync boundaries) the merges. Gate
        placement matches CollectiveSSP (step, clock++, publish, wait)."""
        loss = self.ps(self.ps.shard_batch(batch))
        for name, t in self.sparse.items():
            keys = np.asarray(self.ps.key_fns[name](batch))
            slots = hash_to_slots_np(keys.reshape(-1), t.num_slots,
                                     t.salt, t.identity)
            self._touched[name].update(np.unique(slots).tolist())
        self.clock += 1
        if self._gate is not None:
            publish_clock(self.gossip, self.clock, False)
            self._gate.wait(self.clock)
        if self.clock % self.sync_every == 0:
            self._sync()
        return float(loss)

    def _sync(self) -> None:
        """One merge round: dense vectors then sparse row blocks, every
        table in sorted-name order so all processes launch the same
        collective sequence."""
        rnd = self.sync_rounds
        if self.nprocs == 1:
            # a merge with zero peers is the IDENTITY — and it must be
            # bitwise (``base + (params − base)`` re-rounds in float, so
            # running the arithmetic would perturb a single-process
            # trajectory away from the raw fused-step run the fast tier
            # pins). Only the bases refresh.
            for name, t in self.dense.items():
                self._dense_base[name] = self._copy(t.params)
            for name, t in self.sparse.items():
                self._touched[name].clear()
                self._sparse_base[name] = {
                    ln: self._copy(leaf) for ln, leaf in self._leaves(t)}
            self.sync_rounds += 1
            self._synced_at = self.clock
            return
        for name in sorted(self.dense):
            t = self.dense[name]
            delta = self._sub(t.params, self._dense_base[name])
            # the plane blocks per collective (SyncPlane.allreduce_sum:
            # one in flight at a time, or Gloo communicator setup races)
            merged = self.plane.allreduce_sum(delta)
            new = self._add(self._dense_base[name], merged)
            t.params = new
            self._dense_base[name] = self._copy(new)
            if self.opt_sync == "avg":
                avg_table_opt_state(t, self.plane)
        for name in sorted(self.sparse):
            self._sync_sparse(rnd, name)
        self.sync_rounds += 1
        self._synced_at = self.clock

    def _sync_sparse(self, rnd: int, name: str) -> None:
        t = self.sparse[name]
        mine = np.asarray(sorted(self._touched[name]), dtype=np.int64)
        self._touched[name].clear()
        # multi-process by construction: nprocs==1 took _sync's identity
        # path, and __init__ rejected bus=None for nprocs>1
        assert self.exchange is not None
        parts = self.exchange.allgather(rnd, name, mine,
                                        timeout=self._xt,
                                        monitor=self._monitor)
        self.union_wire_bytes += sum(int(p.nbytes) for p in parts)
        union = (np.unique(np.concatenate(parts))
                 if any(p.size for p in parts) else mine)
        if union.size == 0:
            return  # nobody touched this table: replicas already agree
        C = sync_block_rows(union.size, self.plane.n_local)
        self.sync_rows_max = max(self.sync_rows_max, C)
        idx = np.full(C, t.num_slots, np.int64)
        idx[: union.size] = union
        idxd = jax.device_put(jnp.asarray(idx, jnp.int32),
                              self._rep_sharding)
        bases = self._sparse_base[name]
        for lname, leaf in self._leaves(t):
            delta = self._rows_delta(leaf, bases[lname], idxd)
            if lname == "emb":
                self._last_emb_len = int(delta.shape[0])
            merged = self.plane.allreduce_sum(delta)
            new_leaf, new_base = self._apply_for(leaf.sharding)(
                leaf, bases[lname], idxd, merged)
            if lname == "emb":
                t.emb = new_leaf
            else:
                setattr(t, lname, new_leaf)
            bases[lname] = new_base

    def finalize(self) -> None:
        """Merge any unsynced tail; afterwards every process holds
        identical tables. All processes call this together (it may launch
        one last round of collectives). Idempotent at the same clock —
        an unmatched extra collective on one process would hang the job."""
        if self.clock != self._synced_at:
            self._sync()

    def fingerprint(self) -> float:
        """One float over ALL synced state — dense params, sparse emb AND
        the sparse optimizer rows (they merge additively every round),
        plus dense opt state when opt_sync='avg' reconciles it. Equal
        across processes after finalize; a broken merge of ANY synced
        leaf breaks the equality, not just a param one."""
        total = 0.0
        for name in sorted(self.dense):
            t = self.dense[name]
            total += float(np.asarray(t.params, dtype=np.float64).sum())
            if self.opt_sync == "avg":
                for leaf in jax.tree.leaves(t.opt_state):
                    if is_avg_leaf(leaf, t.padded):
                        total += float(np.asarray(leaf,
                                                  dtype=np.float64).sum())
        for name in sorted(self.sparse):
            for _, leaf in self._leaves(self.sparse[name]):
                total += float(np.asarray(leaf, dtype=np.float64).sum())
        return total


# --------------------------------------------------------------- runners
def run_wd_cssp(args, rank: int, nprocs: int, multi: bool,
                watchdog) -> int:
    """multihost_example ``--model wd --mode bsp|ssp|asp``: the flagship
    DeepFM (hashed wide + field embeddings + deep tower) under the
    collective-gated consistency axis. Emits the smoke-protocol JSON
    line with the row-sparse traffic observables."""
    import json

    from minips_tpu.apps.wide_deep_example import build
    from minips_tpu.core.config import Config, TableConfig, TrainConfig
    from minips_tpu.data import synthetic

    staleness = staleness_for(args.mode, args.staleness)
    if getattr(args, "sync_comm", "float32") != "float32":
        raise SystemExit(
            "--sync-comm compression is not wired for the wd row-sparse "
            "merge (the error-feedback residual is defined over a "
            "per-round-changing row union — per-slot EF bookkeeping is "
            "future work); use --model lr or lm")
    if args.batch % nprocs:
        raise SystemExit(f"--batch {args.batch} must divide by {nprocs} "
                         "processes")
    per = args.batch // nprocs

    def build_fn(mesh):
        cfg = Config(
            table=TableConfig(name="ctr", kind="sparse",
                              updater=args.updater, lr=args.lr,
                              dim=args.dim, num_slots=args.num_slots),
            train=TrainConfig(batch_size=per, num_iters=args.iters),
        )
        ps, (wide_t, emb_t, deep_t) = build(cfg, use_fm=True, mesh=mesh,
                                            seed=args.seed)
        return ps, {"wide": wide_t, "emb": emb_t, "deep": deep_t}

    t0 = time.monotonic()
    trainer = CollectiveSSPPS(
        build_fn, staleness=staleness, sync_every=args.sync_every,
        bus=getattr(watchdog, "bus", None),
        monitor=getattr(watchdog, "monitor", None),
        opt_sync=getattr(args, "opt_sync", "local"))
    # ONE dataset (one ground truth) on every rank; batches sampled with
    # a shared stream, each rank training on its row slice
    data = synthetic.criteo_like(8192, seed=args.seed)
    rng = np.random.default_rng(args.seed)
    jitter_rng = np.random.default_rng(1000 + rank)
    losses = []
    with watchdog.absorbing():  # dead peer ⇒ instant Gloo error in sync
        for i in range(args.iters):
            sel = rng.integers(0, data["y"].shape[0], size=args.batch)
            if args.slow_ms and rank == args.slow_rank:
                time.sleep(args.slow_ms / 1000.0)
            if args.jitter_ms and jitter_rng.random() < args.jitter_prob:
                time.sleep(args.jitter_ms / 1000.0)
            lo, hi = rank * per, (rank + 1) * per
            losses.append(trainer.step(
                {k: v[sel][lo:hi] for k, v in data.items()}))
        # finalize + fingerprint are collectives too — keep them under
        # the same death translation
        trainer.finalize()
        fp = trainer.fingerprint()
    hlo = trainer.sync_hlo() if trainer._last_emb_len else ""

    from minips_tpu.comm import cluster

    watchdog.disarm()
    cluster.barrier("cssp_wd_done")
    print(json.dumps({
        "rank": rank, "event": "done", "model": "wd", "mode": args.mode,
        "wall_s": round(time.monotonic() - t0, 4),
        "multi": multi, "process_count": nprocs,
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "staleness": (None if staleness == float("inf")
                      else int(staleness)),
        "sync_every": args.sync_every,
        "opt_sync": getattr(args, "opt_sync", "local"),
        "loss_first": losses[0], "loss_last": losses[-1],
        "losses": [round(x, 8) for x in losses],
        "param_fingerprint": fp,
        "gate_waits": trainer.gate_waits,
        "max_skew_seen": trainer.max_skew_seen,
        "sync_rounds": trainer.sync_rounds,
        "sync_rows_max": trainer.sync_rows_max,
        "num_slots": int(args.num_slots),
        "union_wire_bytes": trainer.union_wire_bytes,
        "sync_hlo_has_all_reduce": "all-reduce" in hlo,
        "sync_plane_devices": len(trainer.sync_mesh.devices.ravel()),
    }), flush=True)
    watchdog.close()
    return 0


def run_lm_cssp(args, rank: int, nprocs: int, multi: bool,
                watchdog) -> int:
    """multihost_example ``--model lm --mode bsp|ssp|asp``: the LM family
    on the collective consistency axis. Each process is a data-parallel
    ISLAND (its local mesh shards batch rows); the cross-process sync is
    CollectiveSSP's dense delta psum over the transformer's raveled
    parameters — sequence parallelism stays intra-island (ring/a2a need
    one mesh spanning the sequence; under the staleness axis the
    processes deliberately do NOT share a mesh, that is the point)."""
    import json

    from minips_tpu.models import transformer as tfm
    from minips_tpu.train.ssp_spmd import CollectiveSSP

    staleness = staleness_for(args.mode, args.staleness)
    if args.batch % nprocs:
        raise SystemExit(f"--batch {args.batch} must divide by {nprocs} "
                         "processes")
    per = args.batch // nprocs
    T = args.seq_len
    model = dict(vocab=64, dim=32, heads=2, depth=2, max_len=T)
    template = tfm.init(jax.random.PRNGKey(args.seed), **model)

    def grad(p, b):
        return tfm.grad_fn(p, b, heads=model["heads"])

    t0 = time.monotonic()
    trainer = CollectiveSSP(
        template, grad, updater=args.updater, lr=args.lr,
        staleness=staleness, sync_every=args.sync_every,
        bus=getattr(watchdog, "bus", None),
        monitor=getattr(watchdog, "monitor", None), name="lm_cssp",
        opt_sync=getattr(args, "opt_sync", "local"),
        sync_comm=getattr(args, "sync_comm", "float32"))
    rng = np.random.default_rng(args.seed)
    jitter_rng = np.random.default_rng(1000 + rank)
    losses = []
    with watchdog.absorbing():  # dead peer ⇒ instant Gloo error in sync
        for i in range(args.iters):
            toks = rng.integers(0, model["vocab"],
                                size=(args.batch, T + 1)).astype(np.int32)
            if args.slow_ms and rank == args.slow_rank:
                time.sleep(args.slow_ms / 1000.0)
            if args.jitter_ms and jitter_rng.random() < args.jitter_prob:
                time.sleep(args.jitter_ms / 1000.0)
            losses.append(trainer.step(
                {"tokens": toks[rank * per:(rank + 1) * per]}))
        # finalize + fingerprint are collectives too — keep them under
        # the same death translation
        trainer.finalize()

        from minips_tpu.comm import cluster

        fp = float(cluster.host_copy(trainer.table.params).sum())
    hlo = trainer.sync_hlo()
    watchdog.disarm()
    cluster.barrier("cssp_lm_done")
    print(json.dumps({
        "rank": rank, "event": "done", "model": "lm", "mode": args.mode,
        "wall_s": round(time.monotonic() - t0, 4),
        "multi": multi, "process_count": nprocs,
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "staleness": (None if staleness == float("inf")
                      else int(staleness)),
        "sync_every": args.sync_every,
        "opt_sync": getattr(args, "opt_sync", "local"),
        "loss_first": losses[0], "loss_last": losses[-1],
        "losses": [round(x, 8) for x in losses],
        "param_fingerprint": fp,
        "gate_waits": trainer.gate_waits,
        "max_skew_seen": trainer.max_skew_seen,
        "sync_rounds": trainer.sync_rounds,
        "sync_hlo_has_all_reduce": "all-reduce" in hlo,
        "sync_plane_devices": len(trainer.sync_mesh.devices.ravel()),
    }), flush=True)
    watchdog.close()
    return 0
