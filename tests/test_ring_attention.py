"""Ring attention vs. the O(T^2) oracle, on the 8-fake-device mesh.

Sequence parallelism is absent in the reference (SURVEY.md §2.2/§5.7) —
these tests cover the rebuild's beyond-parity long-context module: exact
blockwise attention with K/V shards rotating over ppermute must match full
attention bit-for-bit (up to fp tolerance) for every (causal, shape) combo.
"""

import jax

import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu.utils.jaxcompat import shard_map
from minips_tpu.parallel.ring_attention import (
    make_ring_attention,
    reference_attention,
    ring_attention_local,
)


def _qkv(B, T, H, D, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_oracle(mesh8, causal):
    B, T, H, D = 2, 64, 4, 16  # T sharded 8 ways -> 8 tokens per device
    q, k, v = _qkv(B, T, H, D)
    attn = make_ring_attention(mesh8, causal=causal)
    out = attn(attn.shard(q), attn.shard(k), attn.shard(v))
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_oracle_4way(mesh4, causal):
    B, T, H, D = 1, 32, 2, 8
    q, k, v = _qkv(B, T, H, D, seed=1)
    attn = make_ring_attention(mesh4, causal=causal)
    out = attn(attn.shard(q), attn.shard(k), attn.shard(v))
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("hk", [1, 2])
@pytest.mark.parametrize("causal", [False, True])
def test_gqa_matches_repeat_oracle(mesh4, causal, hk):
    """GQA through the ring: K/V shards rotate at the SMALL head count
    (the ppermute wire shrinks by the group factor); result must equal
    the explicit repeat-KV full-head oracle."""
    B, T, H, D = 1, 32, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, hk, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, hk, D), jnp.float32)
    attn = make_ring_attention(mesh4, causal=causal)
    out = attn(attn.shard(q), attn.shard(k), attn.shard(v))
    want = reference_attention(q, jnp.repeat(k, H // hk, axis=2),
                               jnp.repeat(v, H // hk, axis=2),
                               causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_single_device_degenerates_to_full_attention():
    """n=1 ring = one online-softmax pass over the whole sequence."""
    B, T, H, D = 2, 16, 2, 8
    q, k, v = _qkv(B, T, H, D, seed=2)
    # run under a size-1 shard_map so axis_name resolves
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    f = shard_map(
        lambda a, b, c: ring_attention_local(a, b, c, causal=True),
        mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
        out_specs=P("data"))
    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(reference_attention(q, k, v, causal=True)),
        rtol=2e-5, atol=2e-5)


def test_scale_override(mesh4):
    B, T, H, D = 1, 16, 1, 4
    q, k, v = _qkv(B, T, H, D, seed=3)
    attn = make_ring_attention(mesh4, scale=0.5)
    out = attn(attn.shard(q), attn.shard(k), attn.shard(v))
    want = reference_attention(q, k, v, scale=0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_memory_is_blockwise(mesh8):
    """The compiled program must move K/V with ring hops (collective-permute)
    and never all-gather the sequence — a regression to gather-then-full-
    attention would reintroduce O(T) per-device memory and [T, T] scores."""
    B, T, H, D = 1, 128, 2, 8
    q, k, v = _qkv(B, T, H, D, seed=4)
    attn = make_ring_attention(mesh8)
    sq, sk, sv = attn.shard(q), attn.shard(k), attn.shard(v)
    hlo = jax.jit(lambda a, b, c: attn(a, b, c)).lower(
        sq, sk, sv).compile().as_text()
    assert "collective-permute" in hlo
    assert "all-gather" not in hlo
    out = attn(sq, sk, sv)
    assert out.sharding.spec == jax.sharding.PartitionSpec(None, "data")
    assert np.isfinite(np.asarray(out)).all()


def test_bf16_inputs_keep_f32_softmax_state(mesh4):
    """bf16 q/k/v may round the matmul INPUTS, but the softmax statistics
    (running max / normalizer / accumulator) must stay f32 — both the
    oracle and the ring path should sit within bf16-input rounding of the
    all-f32 result, and the ring must agree with the oracle at much
    tighter than bf16 resolution (both consume identical bf16 inputs)."""
    B, T, H, D = 1, 32, 2, 8
    q, k, v = _qkv(B, T, H, D, seed=7)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    want = reference_attention(q, k, v, causal=True)
    ref_b = reference_attention(qb, kb, vb, causal=True)
    assert ref_b.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(ref_b, np.float32),
                               np.asarray(want), rtol=5e-2, atol=5e-2)

    attn = make_ring_attention(mesh4, causal=True)
    out_b = attn(attn.shard(qb), attn.shard(kb), attn.shard(vb))
    assert out_b.dtype == jnp.bfloat16
    # same bf16 inputs on both sides: only the (f32) accumulation order
    # differs, so agreement must be near-exact — this catches any
    # regression to bf16 carries, which would drift with ring steps
    np.testing.assert_allclose(np.asarray(out_b, np.float32),
                               np.asarray(ref_b, np.float32),
                               rtol=1e-2, atol=1e-2)
