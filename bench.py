"""Benchmark harness — emits ONE JSON line for the driver.

Metric (BASELINE.json:2): **samples/sec/chip, LR + MLP on Criteo**. The
reference publishes no numbers (BASELINE.json:14 "published": {}); the only
quantitative anchor is the north-star target of >= 1M samples/sec aggregate
on a TPU v4-32 for LR + 3-layer MLP on Criteo with SSP staleness <= 4
(BASELINE.json:3-4). A v4-32 slice has 16 chips, so the per-chip target is
1e6 / 16 = 62,500 samples/sec/chip; ``vs_baseline`` reports our measured
samples/sec/chip divided by that target (>1.0 beats the north-star rate
per chip).

What runs (both fused SPMD steps on Criteo-shaped batches, steady-state
timed after compile warmup; every sample passes through BOTH models, so the
reported rate is the end-to-end LR+MLP pipeline rate):

1. **LR**: sparse logistic regression — hashed wide table (26 categorical
   fields) + dense 13-feature linear term.
2. **MLP**: 3-layer tower over [13 dense ; 26 x 8 hashed embeddings], the
   "3-layer MLP on Criteo" shape.

Usage: python bench.py [--cpu] [--iters N] [--batch B]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _tpu_responsive(timeout_s: float = 180.0) -> bool:
    """Probe the real chip in a SUBPROCESS: a hung axon tunnel blocks ops
    forever in-process and cannot be cancelled, so the probe must be
    killable. 180s covers a slow first compile (~20-40s normally)."""
    import subprocess

    code = ("import jax, jax.numpy as jnp;"
            "assert jax.default_backend() == 'tpu', jax.default_backend();"
            "x = jnp.ones((8, 8));"
            "jax.block_until_ready(x @ x);"
            "print('ok')")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True, text=True)
        return proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (8 fake devices) for development")
    ap.add_argument("--iters", type=int, default=60)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--batch", type=int, default=16384)
    args = ap.parse_args()
    if args.iters < 1:
        ap.error("--iters must be >= 1")

    device_note = "tpu"
    if not args.cpu and not _tpu_responsive():
        # The axon tunnel to the one real chip can stall indefinitely (ops
        # hang, not fail). Rather than hang the driver, fall back to the
        # 8-fake-CPU-device mesh and say so in the JSON line.
        print("bench: TPU unresponsive within probe timeout; "
              "falling back to CPU mesh", file=sys.stderr)
        args.cpu = True
        device_note = "cpu-fallback(tpu-unresponsive)"
    if args.cpu:
        import os
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
        if device_note == "tpu":
            device_note = "cpu"
    import jax
    import jax.numpy as jnp

    from minips_tpu.core.config import Config, TableConfig, TrainConfig
    from minips_tpu.data import synthetic
    from minips_tpu.models import lr as lr_model
    from minips_tpu.models import wide_deep as wd_model
    from minips_tpu.parallel.mesh import make_mesh
    from minips_tpu.tables.dense import DenseTable
    from minips_tpu.tables.sparse import SparseTable
    from minips_tpu.train.ps_step import PSTrainStep

    n_chips = len(jax.devices())
    mesh = make_mesh()
    B = args.batch
    data = synthetic.criteo_like(B, seed=0)

    # ---------------- model 1: sparse LR (wide table + dense linear) -------
    wide_t = SparseTable(1 << 18, 1, mesh, name="wide", updater="adagrad",
                         lr=0.05, init_scale=0.0, salt=1)
    lin_t = DenseTable(lr_model.init(13), mesh, name="lin",
                       updater="adagrad", lr=0.05)

    def lr_loss(dp, rows, batch):
        logits = (jnp.sum(rows["wide"][..., 0], axis=-1)
                  + lr_model.logits_dense(dp, batch["dense"]))
        return lr_model.bce_with_logits(logits, batch["y"])

    lr_step = PSTrainStep(lr_loss, dense=lin_t, sparse={"wide": wide_t},
                          key_fns={"wide": lambda b: b["cat"]})

    # ---------------- model 2: 3-layer MLP over dense + embeddings ---------
    emb_t = SparseTable(1 << 18, 8, mesh, name="emb", updater="adagrad",
                        lr=0.05, init_scale=0.01, salt=2)
    deep_t = DenseTable(
        wd_model.init_deep(jax.random.PRNGKey(0), 26, 8, 13,
                           hidden=(256, 128)),
        mesh, name="deep", updater="adam", lr=1e-3)

    def mlp_loss(dp, rows, batch):
        bsz = rows["emb"].shape[0]
        x = jnp.concatenate([batch["dense"], rows["emb"].reshape(bsz, -1)],
                            axis=-1)
        from minips_tpu.models import mlp as mlp_model
        logits = mlp_model.apply(dp, x)[:, 0]
        return lr_model.bce_with_logits(logits, batch["y"])

    mlp_step = PSTrainStep(mlp_loss, dense=deep_t, sparse={"emb": emb_t},
                           key_fns={"emb": lambda b: b["cat"]})

    batch = lr_step.shard_batch(data)

    # ---------------- measure: every sample goes through BOTH models -------
    for _ in range(args.warmup):
        lr_step(batch)
        mlp_step(batch)
    jax.block_until_ready(lr_step.dense.params)
    jax.block_until_ready(mlp_step.dense.params)
    t0 = time.monotonic()
    for _ in range(args.iters):
        l1 = lr_step(batch)
        l2 = mlp_step(batch)
    jax.block_until_ready((l1, l2))
    dt = time.monotonic() - t0

    samples = args.iters * B
    sps_per_chip = samples / dt / n_chips
    target_per_chip = 1_000_000 / 16  # north-star on v4-32 (16 chips)
    print(json.dumps({
        "metric": "samples/sec/chip (LR+MLP on Criteo-shaped, fused SPMD)",
        "value": round(sps_per_chip, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps_per_chip / target_per_chip, 4),
        "device": device_note,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
