"""Collective-traffic accounting from compiled HLO.

VERDICT round-1 task 6 asks for proof that the row-sharded embedding
pull/push does NOT degrade to "all-gather the table": communicated bytes
must scale with the *touched rows* (batch), never with table capacity
(SURVEY.md §7.4.2 "sparse push/pull at 1M samples/sec"). The reference has
the same sparsity property structurally — its Mailbox ships only the
key/val slices for one batch (SURVEY.md §3.3) — so this is a parity
invariant, not just a perf nicety.

This module extracts every cross-device collective from a compiled
executable's HLO and sums the bytes each moves, so tests and benches can
assert the invariant mechanically: compile the same pull/push at two table
sizes and require identical collective traffic; grow the batch and require
proportional growth (tests/test_sharded_traffic.py).

Parsing compiled HLO text is deliberate: post-SPMD-partitioning HLO is the
ground truth of what XLA actually scheduled on the interconnect, whereas
the traced jaxpr only shows what we *asked* for.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

_COLLECTIVES = (
    "all-gather",
    "all-reduce",
    "all-to-all",
    "reduce-scatter",
    "collective-permute",
)

# HLO primitive-type → bytes per element. Sub-byte types (u4/s4, fp8) round
# up to 1; anything not listed falls back to a conservative 8 bytes with a
# warning (overestimating keeps the "traffic is small" guards sound) rather
# than crashing on newer-hardware HLO.
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "f8e5m2": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1, "f8e3m4": 1, "f4e2m1fn": 1,
    "f8e8m0fnu": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# e.g.:  %all-reduce.1 = f32[1024,64]{1,0} all-reduce(%fusion), ...
#        %ag = (s32[8]{0}, s32[8]{0}) all-gather(...)   (tuple results)
_OP_RE = re.compile(
    r"=\s*(?P<result>\([^)]*\)|\S+?)\s+"
    r"(?P<op>" + "|".join(_COLLECTIVES) + r")(?:-start|-done)?\(")
# full HLO primitive-type names (f8e4m3fn, bf16, u4, ...): letters and
# digits interleave, so the name is letter-led alphanumeric — anchored by
# the [dims] bracket that only type names carry in shape position
_SHAPE_RE = re.compile(r"(?P<dtype>[a-z][a-z0-9]*)\[(?P<dims>[\d,]*)\]")


@dataclass(frozen=True)
class CollectiveOp:
    """One cross-device collective in compiled HLO."""
    kind: str      # all-gather / all-reduce / ...
    shape: str     # e.g. "f32[1024,64]"
    bytes: int     # payload size of the result
    # parsed result dims, one tuple per array in the (possibly tuple-)
    # result — guards compare these as INTEGERS (substring matching on
    # `shape` false-positives, e.g. 16384 inside f32[163840])
    dims: tuple = ()

    def has_dim(self, n: int) -> bool:
        return any(n in d for d in self.dims)


def _shape_bytes(shape_text: str, largest: bool = False):
    """(bytes, shapes, dims) across every array shape in ``shape_text``;
    ``largest=True`` returns only the biggest element's bytes (async
    ``-start`` tuples alias the operand next to the output)."""
    sizes, shapes, dims = [], [], []
    for m in _SHAPE_RE.finditer(shape_text):
        dt = m.group("dtype")
        if dt == "token":  # control-dependency tokens carry no payload
            continue
        n = 1
        for d in m.group("dims").split(","):
            if d:
                n *= int(d)
        per_elem = _DTYPE_BYTES.get(dt)
        if per_elem is None:
            import warnings
            warnings.warn(f"unknown HLO primitive type {dt!r}; assuming "
                          "16 bytes/element (conservative)", stacklevel=3)
            per_elem = 16  # >= the widest known type (c128)
        sizes.append(n * per_elem)
        shapes.append(f"{dt}[{m.group('dims')}]")
        dims.append(tuple(int(d) for d in m.group("dims").split(",") if d))
    total = (max(sizes) if largest else sum(sizes)) if sizes else 0
    return total, shapes, tuple(dims)


def collective_ops(hlo_text: str) -> list[CollectiveOp]:
    """All cross-device collectives in (post-partitioning) HLO text.

    ``bytes`` is the per-device result payload — the quantity that rides
    the interconnect once per device. Async ``-start``/``-done`` pairs are
    counted once, on the ``-start`` line. An async ``-start`` result is a
    TUPLE that aliases the operand alongside the output (e.g.
    ``(f32[512,32], f32[4096,32]) all-gather-start`` — operand, output —
    and ``collective-permute-start`` adds u32[] context scratch), so
    summing the tuple would double-count the payload: for ``-start`` ops
    we take the LARGEST element (the output; for permute in/out are the
    same shape, so either is the single payload). Sync variadic
    collectives (tuple-result ``all-reduce`` over several operands) do
    move every element, so those still sum.
    """
    ops: list[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None or f"{m.group('op')}-done(" in line:
            continue
        is_start = f"{m.group('op')}-start(" in line
        nbytes, shapes, dims = _shape_bytes(m.group("result"),
                                            largest=is_start)
        ops.append(CollectiveOp(m.group("op"), " ".join(shapes), nbytes,
                                dims))
    return ops


def collective_bytes(compiled) -> int:
    """Total collective payload bytes per device for a compiled executable
    (the output of ``jax.jit(f).lower(*args).compile()``)."""
    return sum(op.bytes for op in collective_ops(compiled.as_text()))


def traffic_report(compiled) -> dict:
    """{total_bytes, ops:[{kind, shape, bytes}...]} — JSONL-friendly, for
    bench output and metrics (SURVEY.md §5.5)."""
    ops = collective_ops(compiled.as_text())
    return {
        "total_bytes": sum(o.bytes for o in ops),
        "ops": [{"kind": o.kind, "shape": o.shape, "bytes": o.bytes}
                for o in ops],
    }
