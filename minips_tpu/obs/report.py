"""Blocked-time attribution over a merged wire trace.

``python -m minips_tpu.obs.report merged_trace.json [--json]``

The straggler observable: for each rank, how much wall time it spent
BLOCKED, split by what it was blocked ON —

- ``owner <r>``: waiting for a pull leg's reply from shard owner ``r``
  (``pull_wait`` spans; when the span's per-leg ``pull_leg`` children
  are present the wait is attributed to the leg that finished LAST
  inside it — the actual straggler — otherwise split evenly over the
  span's owners);
- ``gate <r>``: the SSP gate waiting for rank ``r``'s clock
  (``gate_wait`` spans, split evenly over the ``behind`` ranks);
- ``fence``: a local read fenced behind an in-flight block migration
  (``fence_wait`` spans).

This table is what every future perf PR reads first: it turns "rank 2
is slow" into "rank 2 spends 38% of its wall blocked, 31% of that on
owner 0's serves" — the difference between guessing and aiming.
"""

from __future__ import annotations

import argparse
import json
import sys
from bisect import bisect_right
from collections import defaultdict
from typing import Optional

from minips_tpu.obs.merge import XLA_PID_BASE

__all__ = ["attribute", "format_table", "main"]


def _span(e: dict) -> tuple[float, float]:
    ts = float(e.get("ts", 0.0))
    return ts, ts + float(e.get("dur", 0.0))


def attribute(doc: dict) -> dict:
    """``{rank: {"wall_us", "blocked_us", "by": {label: us}}}`` over a
    merged (or single-rank) trace document. Device processes an
    ``--xla`` interleave added (pid >= merge.XLA_PID_BASE) are not
    ranks and stay out of the table."""
    events = doc.get("traceEvents", ())
    by_rank: dict[int, list[dict]] = defaultdict(list)
    for e in events:
        if e.get("ph") == "X" and int(e.get("pid", 0)) < XLA_PID_BASE:
            by_rank[int(e.get("pid", 0))].append(e)
    out: dict[int, dict] = {}
    for rank, evs in sorted(by_rank.items()):
        lo = min(_span(e)[0] for e in evs)
        hi = max(_span(e)[1] for e in evs)
        by: dict[str, float] = defaultdict(float)
        # legs sorted by END time once per rank: each wait span then
        # finds its last-finishing leg by bisection — a full-ring trace
        # has tens of thousands of each, and the quadratic rescan this
        # replaces took minutes on exactly the traces the tool is for
        legs = sorted((e for e in evs if e.get("name") == "pull_leg"),
                      key=lambda g: _span(g)[1])
        leg_ends = [_span(g)[1] for g in legs]
        for e in evs:
            name = e.get("name")
            t0, t1 = _span(e)
            dur = t1 - t0
            if dur <= 0.0:
                continue
            args = e.get("args") or {}
            if name == "pull_wait":
                # prefer the actual straggler: the leg whose reply
                # landed last inside this wait span — with leg_ends
                # sorted, walk left from the rightmost end <= t1
                # (+jitter) while still inside the window. The leg
                # must belong to one of THIS wait's owners: with
                # prefetch overlap another table/group's leg routinely
                # completes inside an unrelated wait span, and blaming
                # its owner would book the whole wait to the wrong
                # shard.
                owners = args.get("owners") or ["?"]
                owner_set = set(owners)
                pick = None
                i = bisect_right(leg_ends, t1 + 1.0) - 1
                while i >= 0 and leg_ends[i] >= t0 - 1.0:
                    o = (legs[i].get("args") or {}).get("owner", "?")
                    if o in owner_set:
                        pick = o
                        break
                    i -= 1
                if pick is not None:
                    by[f"owner {pick}"] += dur
                else:
                    for o in owners:
                        by[f"owner {o}"] += dur / len(owners)
            elif name == "gate_wait":
                behind = args.get("behind") or ["?"]
                for p in behind:
                    by[f"gate {p}"] += dur / len(behind)
            elif name == "fence_wait":
                by["fence"] += dur
        blocked = sum(by.values())
        out[rank] = {
            "wall_us": round(hi - lo, 1),
            "blocked_us": round(blocked, 1),
            "blocked_frac": round(blocked / (hi - lo), 4)
            if hi > lo else 0.0,
            "by": {k: round(v, 1)
                   for k, v in sorted(by.items(),
                                      key=lambda kv: -kv[1])},
        }
    return out


def format_table(attr: dict) -> str:
    """The human table (one rank per row, top-3 attributions)."""
    lines = [f"{'rank':>4}  {'wall_ms':>9}  {'blocked':>8}  "
             f"top blocked-on"]
    for rank, r in sorted(attr.items()):
        wall = r["wall_us"]
        tops = list(r["by"].items())[:3]
        top_s = ", ".join(
            f"{k} {100.0 * v / wall:.1f}%" for k, v in tops) or "-"
        lines.append(
            f"{rank:>4}  {wall / 1e3:>9.1f}  "
            f"{100.0 * r['blocked_frac']:>7.1f}%  {top_s}")
    return "\n".join(lines)


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Blocked-time attribution table from a merged "
                    "wire trace")
    ap.add_argument("trace", help="merged_trace.json (obs.merge output)")
    ap.add_argument("--json", action="store_true",
                    help="emit the attribution dict instead of the "
                         "table")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    attr = attribute(doc)
    if not attr:
        print("report: no complete events in trace", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({str(k): v for k, v in attr.items()}))
    else:
        print(format_table(attr))
    return 0


if __name__ == "__main__":
    sys.exit(main())
