from minips_tpu.core.config import Config, TableConfig, TrainConfig  # noqa: F401
from minips_tpu.core.engine import Engine, Info, MLTask  # noqa: F401
