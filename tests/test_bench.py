"""bench.py harness contract: one JSON line, FLOP-accounted fields, and
the off-TPU vs_baseline refusal (VERDICT r1 weak #7 / next-round #2)."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = str(pathlib.Path(__file__).resolve().parents[1])


@pytest.mark.slow
def test_bench_cpu_emits_accounted_json():
    proc = subprocess.run(
        [sys.executable, "bench.py", "--cpu", "--suite", "lrmlp",
         "--batch", "512", "--chain", "2", "--reps", "2"],
        capture_output=True, text=True, timeout=420, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    assert out["unit"] == "samples/sec/chip"
    assert out["value"] > 0
    # a CPU run must never publish a TPU-comparable ratio
    assert out["vs_baseline"] is None
    s = out["suites"]["lrmlp"]
    assert s["tflops_per_chip"] > 0
    assert "mfu_vs_bf16_peak" in s and s["mfu_vs_bf16_peak"] is None
    assert "warning" not in s


@pytest.mark.slow
@pytest.mark.parametrize("suite", ["mf", "w2v"])
def test_bench_embedding_suites_cpu(suite):
    """Round-3 suites for BASELINE configs 3 (MF/MovieLens) and 5
    (word2vec/enwiki): same harness contract — one JSON line, accounted
    fields, off-TPU vs_baseline refusal."""
    proc = subprocess.run(
        [sys.executable, "bench.py", "--cpu", "--suite", suite,
         "--batch", "512", "--chain", "2", "--reps", "2"],
        capture_output=True, text=True, timeout=420, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    assert out["unit"] == "samples/sec/chip"
    assert out["value"] > 0
    assert out["vs_baseline"] is None          # off-TPU refusal holds
    assert suite in out["metric"]              # never labeled as LR+MLP
    s = out["suites"][suite]
    assert s["tflops_per_chip"] > 0
    assert s["mfu_vs_bf16_peak"] is None
    assert "warning" not in s


def test_sharded_ps_bench_worker_standalone():
    """Zero-wire baseline mode (no launcher): the worker runs, counts, and
    reports the protocol fields — the n=1 point of bench_sharded_ps.py."""
    proc = subprocess.run(
        [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
         "--path", "sparse", "--iters", "8", "--warmup", "2",
         "--rows", "4096", "--batch", "512"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert out["event"] == "done" and out["nprocs"] == 1
    assert out["bus"] == "none"
    assert out["rows_per_sec"] > 0
    assert out["wire_push_bytes_per_sec"] == 0  # nothing rides a wire


def test_sharded_ps_bench_worker_jit_compute():
    """--compute jit (the ps_tpu suite's worker): a real jitted MLP grad
    runs on the pulled rows between pull and push. Forced-CPU here (the
    chip leg engages only when the bench's probe says it is alive); the
    result must label the backend and still count rows/wire."""
    proc = subprocess.run(
        [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
         "--path", "sparse", "--iters", "8", "--warmup", "2",
         "--rows", "4096", "--batch", "512", "--compute", "jit",
         "--hidden", "64"],
        capture_output=True, text=True, timeout=180,
        cwd=REPO, env={**os.environ, "MINIPS_FORCE_CPU": "1"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads([ln for ln in proc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert out["event"] == "done" and out["compute"] == "jit(cpu)"
    assert out["rows_per_sec"] > 0


@pytest.mark.slow
def test_sharded_ps_bench_floor_two_processes():
    """Regression floor for the sharded-PS data path (VERDICT r2 #2): a
    2-process loopback sparse pull+push must sustain >100k rows/sec per
    process (measured ~1.5M on this class of host — 15x headroom so CI
    noise can't flake it) and drop zero frames (asserted in-worker)."""
    from minips_tpu import launch

    res = launch.run_local_job(
        2, [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
            "--path", "sparse", "--iters", "24", "--warmup", "4"],
        base_port=6590, timeout=240.0)
    assert len(res) == 2
    for r in res:
        assert r["event"] == "done" and r["nprocs"] == 2
        assert r["rows_per_sec"] > 100_000, r
        assert r["wire_push_bytes_per_sec"] > 0  # wire actually engaged


def test_tpu_probe_sentinel_classification(monkeypatch):
    """ADVICE r4 low: the probe's permanent-vs-retryable call keys on
    sentinels the probe SUBPROCESS emits, not on parsing jax's stderr in
    the parent with a wall-clock bound. Absent platform → permanent;
    init failure, crash, or hang → retryable."""
    import types

    sys.path.insert(0, REPO)
    import bench

    def fake(stdout, rc):
        def run(cmd, timeout=None, capture_output=None, text=None):
            return types.SimpleNamespace(returncode=rc, stdout=stdout,
                                         stderr="")
        return run

    monkeypatch.setattr("subprocess.run", fake("MINIPS_PROBE_OK\n", 0))
    assert bench._tpu_responsive(5) == (True, False)
    monkeypatch.setattr("subprocess.run", fake("MINIPS_PROBE_NO_TPU\n", 3))
    assert bench._tpu_responsive(5) == (False, True)
    monkeypatch.setattr("subprocess.run",
                        fake("MINIPS_PROBE_INIT_FAILED\n", 3))
    assert bench._tpu_responsive(5) == (False, False)
    monkeypatch.setattr("subprocess.run", fake("", 1))  # raw crash
    assert bench._tpu_responsive(5) == (False, False)

    def hang(cmd, timeout=None, capture_output=None, text=None):
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr("subprocess.run", hang)
    assert bench._tpu_responsive(5) == (False, False)


def test_ssp_schedule_simulation_invariants():
    """The event-driven gate schedule (bench_ssp.simulate_schedule) obeys
    the theory: BSP pays the union of stalls, staleness only helps, zero
    jitter makes all modes equal, and large s approaches the no-barrier
    bound (slowest worker's own work)."""
    sys.path.insert(0, REPO)
    from bench_ssp import simulate_schedule

    kw = dict(n=3, iters=200, step_ms=20.0, jitter_ms=40.0,
              jitter_prob=0.25, seed=1)
    bsp = simulate_schedule(staleness=0, **kw)
    ssp = simulate_schedule(staleness=4, **kw)
    free = simulate_schedule(staleness=10**6, **kw)
    assert free <= ssp <= bsp
    assert bsp > ssp * 1.05            # jitter regime: SSP genuinely wins
    # no jitter: the barrier costs nothing, every mode identical
    kw0 = dict(kw, jitter_ms=0.0)
    assert simulate_schedule(staleness=0, **kw0) == \
        simulate_schedule(staleness=4, **kw0)
    # the no-barrier bound equals the slowest worker's own serial time
    import numpy as np
    rng = np.random.default_rng(1)
    stall = (rng.random((3, 200)) < 0.25) * 40.0
    serial = (200 * 20.0 + stall.sum(axis=1)).max() / 1000.0
    assert abs(free - serial) < 1e-9
