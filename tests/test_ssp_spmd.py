"""CollectiveSSP (train/ssp_spmd.py) — fast-tier units.

The real 2-process legs (gate engagement under a straggler, oracle loss
parity, cross-rank agreement) live in tests/test_multihost.py's slow
tier; here the single-process degenerate forms pin the local math: a
P=1 sync must be an exact no-op on the parameters (base + own delta =
params), the merge program must compile to a collective, and the
schedule bookkeeping must match the configuration.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu.models import lr as lr_model
from minips_tpu.train.ssp_spmd import CollectiveSSP


def _batch(rng, n=32, d=16):
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    return {"x": x, "y": y}


def test_collective_ssp_single_process_trains(mesh8):
    rng = np.random.default_rng(0)
    tr = CollectiveSSP(lr_model.init(16), lr_model.grad_fn_dense,
                       lr=0.3, sync_every=2)
    losses = [tr.step(_batch(rng)) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert tr.clock == 6 and tr.sync_rounds == 3
    assert np.isfinite(np.asarray(tr.table.params)).all()


def test_collective_ssp_sync_is_identity_at_world_one(mesh8):
    """P=1: psum over a size-1 proc axis returns my own delta, so
    base + delta must EXACTLY reproduce the pre-sync parameters — any
    drift here would be silent corruption at every world size."""
    rng = np.random.default_rng(1)
    tr = CollectiveSSP(lr_model.init(16), lr_model.grad_fn_dense,
                       lr=0.3, sync_every=10_000)  # never auto-syncs
    for _ in range(3):
        tr.step(_batch(rng))
    before = np.asarray(tr.table.params).copy()
    tr._sync()
    np.testing.assert_array_equal(np.asarray(tr.table.params), before)
    assert tr.sync_rounds == 1
    # and base was refreshed: an immediate re-sync is also the identity
    tr._sync()
    np.testing.assert_array_equal(np.asarray(tr.table.params), before)


def test_collective_ssp_finalize_merges_tail(mesh8):
    rng = np.random.default_rng(2)
    tr = CollectiveSSP(lr_model.init(16), lr_model.grad_fn_dense,
                       lr=0.3, sync_every=4)
    for _ in range(6):  # 4 + a 2-step tail
        tr.step(_batch(rng))
    assert tr.sync_rounds == 1
    tr.finalize()
    assert tr.sync_rounds == 2
    tr.finalize()  # aligned clock: no extra collective
    assert tr.sync_rounds == 2


def test_collective_ssp_sync_program_is_a_collective(mesh8):
    """The comm_analysis hook: the cross-host sync compiles to an XLA
    all-reduce — parameter bytes ride the collective plane, never the
    zmq bus (SURVEY §7.4.1, BASELINE.json:5)."""
    tr = CollectiveSSP(lr_model.init(16), lr_model.grad_fn_dense)
    assert "all-reduce" in tr.sync_hlo()


def test_collective_ssp_rejects_bad_sync_every(mesh8):
    with pytest.raises(ValueError, match="sync_every"):
        CollectiveSSP(lr_model.init(8), lr_model.grad_fn_dense,
                      sync_every=0)


def test_collective_ssp_multiprocess_without_bus_refuses(mesh8,
                                                         monkeypatch):
    """nprocs > 1 with no control bus means no clock gossip: the gate
    would silently not exist while skew grows to sync_every. Refuse
    loudly unless staleness >= sync_every (where the collective
    rendezvous itself is the tighter bound)."""
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="control bus"):
        CollectiveSSP(lr_model.init(8), lr_model.grad_fn_dense,
                      staleness=2, sync_every=8)


def test_collective_ssp_local_step_stays_on_local_devices(mesh8):
    """The local data plane must never enlist remote devices: params and
    opt state live on the per-process mesh only."""
    tr = CollectiveSSP(lr_model.init(16), lr_model.grad_fn_dense)
    local = set(jax.local_devices())
    assert set(tr.table.params.sharding.device_set) <= local
    for leaf in jax.tree.leaves(tr.table.opt_state):
        if hasattr(leaf, "sharding"):
            assert set(leaf.sharding.device_set) <= local
    # while the sync plane spans every device of every process
    assert (set(tr.sync_mesh.devices.ravel().tolist())
            == set(jax.devices()))
