"""TrainLoop — the driver's Run() loop for SPMD apps.

Threads together the pieces the reference scatters across Engine::Run and
the app UDF (SURVEY.md §3.2-3.3): data iteration, the fused step, JSONL
metrics with samples/sec (the [T1] primary metric), optional periodic
checkpointing, and the consistency clock (for observability; on the pure
SPMD path BSP is implicit in the collectives).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from minips_tpu.utils.metrics import MetricsLogger
from minips_tpu.utils.timing import StepTimer


class TrainLoop:
    def __init__(
        self,
        step: Callable[[Any], Any],
        data: Iterable[Any],
        *,
        metrics: Optional[MetricsLogger] = None,
        log_every: int = 10,
        batch_size: Optional[int] = None,
        checkpointer=None,
        checkpoint_every: int = 0,
        warmup_steps: int = 2,
        step_offset: int = 0,
        profile_dir: Optional[str] = None,
        profile_range: tuple[int, int] = (10, 13),
        prefetch: Optional[Callable[[Any], None]] = None,
        extra_metrics: Optional[Callable[[], dict]] = None,
    ):
        self.step = step
        self.data = data
        # ``prefetch(next_batch)`` is called with batch t+1 BEFORE
        # ``step(batch t)`` runs — the overlap hook for PS-backed steps:
        # a sharded-PS app passes a callable that issues
        # ``table.prefetch_pull(keys_of(next_batch))`` so the pull round
        # trip rides under this step's compute (train/sharded_ps.py
        # pipeline). Costs one batch of lookahead in the data stream;
        # None (the default) keeps the loop strictly sequential.
        self.prefetch = prefetch
        # ``extra_metrics()`` is splatted into every periodic log line —
        # the hook PS-backed loops use to surface wire/cache health
        # (``utils.metrics.wire_record``: bytes both ways, per-leg
        # timing, row-cache hit rate) next to loss without the loop
        # knowing what a trainer is. Keep it cheap: it runs every
        # ``log_every`` steps on the training thread.
        self.extra_metrics = extra_metrics
        self.metrics = metrics or MetricsLogger(verbose=False)
        self.log_every = log_every
        self.batch_size = batch_size
        self.checkpointer = checkpointer
        self.checkpoint_every = checkpoint_every
        # Global step numbering continues across resumes: without the
        # offset, a resumed run would re-save low step numbers and a later
        # restore() would pick an old-numbered-but-newer checkpoint.
        self.step_offset = step_offset
        self.timer = StepTimer(warmup_steps=warmup_steps)
        self.profiler = None
        if profile_dir:
            from minips_tpu.utils.profiling import StepWindowProfiler

            self.profiler = StepWindowProfiler(profile_dir, *profile_range)

    def run(self, num_iters: int) -> list[float]:
        try:
            return self._run(num_iters)
        finally:
            if self.profiler is not None:
                self.profiler.close()  # an open trace must flush even on error

    def _run(self, num_iters: int) -> list[float]:
        losses: list[float] = []
        # Resume continues the data stream, not just the step numbering: a
        # data source with iter_from (BatchIterator) is fast-forwarded to
        # the global step so a resumed run sees exactly the batches the
        # uninterrupted run would have seen from there.
        if self.step_offset and hasattr(self.data, "iter_from"):
            it = self.data.iter_from(self.step_offset)
        else:
            if self.step_offset:
                # e.g. a bare generator: we cannot fast-forward it, so the
                # exact-replay-on-resume guarantee is the caller's problem
                self.metrics.log(
                    warning="resume: data source has no iter_from; stream "
                            "starts wherever the caller left it")
            it = iter(self.data)
        ahead = None  # batch t+1, already announced through prefetch
        for i in range(num_iters):
            if self.profiler is not None:
                self.profiler.on_step(i)
            if ahead is not None:
                batch, ahead = ahead, None
            else:
                try:
                    batch = next(it)
                except StopIteration:
                    # finite sources (one-pass streams) end the loop
                    # cleanly; BatchIterator-style sources cycle and
                    # never raise
                    self.metrics.log(event="stream_exhausted",
                                     step=self.step_offset + i)
                    break
            if self.prefetch is not None:
                # announce batch t+1 before stepping batch t, so a
                # PS-backed step's pull round trip overlaps this step's
                # compute; a batch prefetched but never stepped (the
                # num_iters bound lands between them) is the callback
                # owner's cleanup (PullFuture.cancel)
                try:
                    ahead = next(it)
                except StopIteration:
                    ahead = None
                else:
                    self.prefetch(ahead)
            loss = self.step(batch)
            n = (self.batch_size if self.batch_size is not None
                 else _leading_dim(batch))
            self.timer.step(n)
            losses.append(float(loss))
            gstep = self.step_offset + i + 1
            if self.log_every and (i + 1) % self.log_every == 0:
                extra = (self.extra_metrics()
                         if self.extra_metrics is not None else {})
                self.metrics.log(step=gstep, loss=float(loss),
                                 samples_per_sec=self.timer.samples_per_sec,
                                 **extra)
            # GLOBAL-step modulo: a resumed run keeps the same checkpoint
            # cadence as an uninterrupted one (local modulo would drift by
            # start_step and can leave resumed tail steps never saved)
            if (self.checkpointer is not None and self.checkpoint_every
                    and gstep % self.checkpoint_every == 0):
                self.checkpointer.save(step=gstep)
        return losses


def _leading_dim(batch) -> int:
    import jax

    leaves = jax.tree.leaves(batch)
    return int(leaves[0].shape[0]) if leaves else 0
