"""Control bus + heartbeat over loopback — threads-as-nodes, the same way
the reference tests its mailbox (SURVEY.md §4)."""

import time

import pytest

from minips_tpu.comm.bus import ClockGossip, ControlBus
from minips_tpu.comm.heartbeat import HeartbeatMonitor


def _mk_buses(n, base_port):
    addrs = [f"tcp://127.0.0.1:{base_port + i}" for i in range(n)]
    buses = [ControlBus(addrs[i], [a for j, a in enumerate(addrs) if j != i],
                        my_id=i) for i in range(n)]
    for b in buses:
        b.start()
    time.sleep(0.2)  # PUB/SUB slow-joiner settle
    return buses


def test_bus_pubsub_roundtrip():
    buses = _mk_buses(2, 15730)
    got = []
    buses[1].on("hello", lambda sender, p: got.append((sender, p["x"])))
    buses[0].publish("hello", {"x": 42})
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.01)
    for b in buses:
        b.close()
    assert got == [(0, 42)]


def test_clock_gossip_global_min():
    buses = _mk_buses(3, 15760)
    gossips = [ClockGossip(b, 3, workers_per_process=2) for b in buses]
    gossips[0].publish_local([5, 6])
    gossips[1].publish_local([3, 9])
    gossips[2].publish_local([7, 7])
    deadline = time.time() + 5
    ok = False
    while time.time() < deadline:
        if all(g.global_min() == 3 for g in gossips):
            ok = True
            break
        time.sleep(0.02)
    for b in buses:
        b.close()
    assert ok, [g.snapshot() for g in gossips]


def test_heartbeat_detects_dead_peer():
    buses = _mk_buses(2, 15790)
    failures = []
    fake_time = [0.0]
    mon = HeartbeatMonitor(buses[0], peer_ids=[0, 1], interval=0.05,
                           timeout=1.0, on_failure=failures.append,
                           clock=lambda: fake_time[0])
    # peer 1 beats at t=0.5 -> alive
    fake_time[0] = 0.5
    mon._on_beat(1, {})
    assert mon.check() == set()
    # silence until t=2.0 -> dead (2.0 - 0.5 > 1.0)
    fake_time[0] = 2.0
    assert mon.check() == {1}
    assert failures == [1]
    # still dead, but on_failure fires only once
    fake_time[0] = 3.0
    mon.check()
    assert failures == [1]
    for b in buses:
        b.close()


def test_heartbeat_live_peer_not_flagged():
    buses = _mk_buses(2, 15820)
    mons = [HeartbeatMonitor(b, peer_ids=[0, 1], interval=0.05, timeout=2.0)
            for b in buses]
    for m in mons:
        m.start()
    time.sleep(0.5)  # several beat intervals
    dead = [m.dead for m in mons]
    for m in mons:
        m.stop()
    for b in buses:
        b.close()
    assert dead == [set(), set()]
